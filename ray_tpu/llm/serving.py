"""The engine behind `ray_tpu.serve`: a multiplexed, streaming LLM
deployment.

Wiring (ISSUE 10 tentpole):

* replicas host `InferenceEngine`s — one engine per loaded model
  family, created through ``@serve.multiplexed`` so the router's
  model-warmth ranking and the per-replica LRU apply unchanged;
  per-family slot accounting falls out of one-engine-per-family;
* ``__call__`` is a GENERATOR, so the deployment is a streaming
  ingress: each sampled token goes out as its own chunk while the
  engine keeps decoding (proxy chunked transfer-encoding, handle
  ``options(stream=True)``);
* admission: the router/proxy queue feeds the replica; the replica
  hands the request to the engine's FIFO scheduler, which rejects
  with ``EngineOverloaded`` past its waiting bound;
* cancellation: a consumer that abandons the stream
  (`DeploymentResponseGenerator.close()`, proxy client disconnect)
  triggers `Replica.cancel_stream` -> ``__serve_cancel_stream__``
  here -> `engine.cancel` — the slot frees mid-decode instead of
  decoding to the token budget for nobody;
* kill switch: ``RT_serve_engine_enabled=0`` (or
  ``engine_enabled=False``) serves every request with a per-request
  `generate_stream()` — the serialize-per-request baseline, same
  response format.

Request payload (HTTP body JSON or a plain dict via handle):

    {"prompt": [token ids], "max_new_tokens": 16,
     "model": "family-id" (optional; `serve_multiplexed_model_id`
      header / handle option wins), "eos_token": optional}

Response stream: one chunk per token, ASCII decimal + trailing space
(client sums/parses trivially; servebench.py times chunk arrivals).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ..serve.multiplex import get_multiplexed_model_id, multiplexed
from .engine import EngineConfig, InferenceEngine

#: Engines a single replica keeps loaded (multiplex LRU bound).
MAX_FAMILIES_PER_REPLICA = 4


def _resolve_dtype(name: Any):
    import jax.numpy as jnp

    if not isinstance(name, str):
        return name
    return {
        "float32": jnp.float32,
        "bfloat16": jnp.bfloat16,
        "float16": jnp.float16,
    }[name]


def build_model(spec: Dict[str, Any]):
    """Model-family spec -> (params, LlamaConfig).

    kind "init": randomly initialized from a config dict (tests,
    servebench — every HF family shares the Llama compute graph, so a
    family here is a (config, seed) point);
    kind "hf": a converted HF checkpoint directory
    (models/hf_convert.load_hf_llama — the six parity-proven
    families)."""
    import jax

    kind = spec.get("kind", "init")
    if kind == "hf":
        from ..models.hf_convert import load_hf_llama

        return load_hf_llama(spec["path"])
    if kind != "init":
        raise ValueError(f"unknown model spec kind {kind!r}")
    from ..models.llama import LlamaConfig, init_params

    kwargs = dict(spec.get("config") or {})
    if "dtype" in kwargs:
        kwargs["dtype"] = _resolve_dtype(kwargs["dtype"])
    kwargs.setdefault("attention", "reference")
    cfg = LlamaConfig(**kwargs)
    params = init_params(
        jax.random.PRNGKey(int(spec.get("seed", 0))), cfg
    )
    return params, cfg


class LLMServer:
    """The deployment class `build_llm_app` wraps (usable directly:
    ``serve.deployment(LLMServer).bind(families, ...)``)."""

    def __init__(
        self,
        families: Dict[str, Dict[str, Any]],
        default_family: Optional[str] = None,
        engine: Optional[Dict[str, Any]] = None,
        engine_enabled: bool = True,
    ):
        if not families:
            raise ValueError("families must name at least one model")
        self._families = dict(families)
        self._default = default_family or next(iter(self._families))
        self._engine_cfg = EngineConfig(**(engine or {}))
        self._engine_enabled = bool(engine_enabled)
        # serve request_id -> [(engine, engine_request_id), ...] for
        # cancel_stream propagation. A LIST per id: the serve id is
        # CLIENT-controlled (x-request-id), so concurrent requests may
        # collide on it — each stream keeps its own engine-minted
        # unique id and cancel hits every stream under the serve id.
        self._streams: Dict[str, list] = {}
        # Cancels that arrived BEFORE their stream handler ran (the
        # cancel RPC can beat the streaming call through the actor
        # mailbox): serve_id -> arrival ts, consulted right after
        # submit. Entries expire; the map stays tiny.
        self._early_cancels: Dict[str, float] = {}
        self._streams_lock = threading.Lock()
        # Fallback (params, cfg) per family, behind the SAME LRU
        # machinery as engines: bounded to MAX_FAMILIES_PER_REPLICA
        # (not an ever-growing dict) and per-family load
        # serialization, so a cold family's load never blocks warm
        # families' requests.
        self._fallback_lock = threading.Lock()
        self._fallback_wrapper = None

    # -- engines -------------------------------------------------------
    @multiplexed(max_num_models_per_replica=MAX_FAMILIES_PER_REPLICA)
    def get_engine(self, family: str) -> InferenceEngine:
        """Loader the multiplex LRU calls on a cold family: builds the
        params and an engine with its OWN slots + step thread, so a
        swap (this load) blocks only requests for THIS family."""
        from ..serve.observability import current_request_context

        spec = self._spec(family)
        params, cfg = build_model(spec)
        ctx = current_request_context() or {}
        return InferenceEngine(
            params,
            cfg,
            self._engine_cfg,
            family=family,
            app=str(ctx.get("app", "")),
            deployment=str(ctx.get("deployment", "")),
        )

    def _spec(self, family: str) -> Dict[str, Any]:
        spec = self._families.get(family)
        if spec is None:
            raise ValueError(
                f"unknown model family {family!r}; serving "
                f"{sorted(self._families)}"
            )
        return spec

    # -- request path --------------------------------------------------
    def __call__(self, request):
        """Streaming ingress: yields one chunk per sampled token."""
        payload = (
            request.json() if hasattr(request, "json") else request
        ) or {}
        family = (
            get_multiplexed_model_id()
            or str(payload.get("model") or "")
            or self._default
        )
        prompt = payload.get("prompt")
        if not prompt:
            raise ValueError("payload needs a non-empty 'prompt'")
        max_new = payload.get("max_new_tokens")
        max_new = None if max_new is None else int(max_new)
        eos = payload.get("eos_token")
        eos = None if eos is None else int(eos)
        if not self._engine_enabled:
            yield from self._serve_fallback(
                family, prompt, max_new, eos
            )
            return
        from ..serve.observability import get_request_id

        engine = self.get_engine(family)
        # The engine mints its own UNIQUE id (a client-controlled
        # x-request-id may collide); the serve id only keys the
        # cancel map.
        stream = engine.submit(
            prompt, max_new_tokens=max_new, eos_token=eos
        )
        serve_id = get_request_id()
        entry = (engine, stream.request_id)
        cancelled_early = False
        if serve_id:
            with self._streams_lock:
                self._streams.setdefault(serve_id, []).append(entry)
                # The consumer may have abandoned us before this
                # handler even ran (cancel RPC beat the streaming
                # call through the mailbox).
                cancelled_early = (
                    self._early_cancels.pop(serve_id, None)
                    is not None
                )
        if cancelled_early:
            stream.cancel()
        try:
            for token in stream:
                yield f"{token} ".encode()
        finally:
            # Abnormal generator exit (consumer gone) must not leave
            # the engine decoding the rest of the budget for nobody.
            if stream.finish_reason is None:
                stream.cancel()
            if serve_id:
                with self._streams_lock:
                    entries = self._streams.get(serve_id)
                    if entries is not None:
                        try:
                            entries.remove(entry)
                        except ValueError:
                            pass
                        if not entries:
                            self._streams.pop(serve_id, None)

    def __serve_cancel_stream__(self, request_id: str) -> bool:
        """Replica cancel hook: the consumer abandoned the stream.
        Cancels EVERY live stream under the serve request id (ids are
        client-controlled and may collide; each entry still cancels
        by its own engine-minted id). A miss is remembered briefly —
        the cancel may have outrun its own stream handler."""
        now = time.time()
        with self._streams_lock:
            entries = list(self._streams.get(request_id, ()))
            if not entries:
                self._early_cancels[request_id] = now
                # Expire stale entries so the map stays bounded even
                # under cancel floods for requests that never arrive.
                for rid, ts in list(self._early_cancels.items()):
                    if now - ts > 60.0:
                        del self._early_cancels[rid]
        cancelled = False
        for engine, engine_request_id in entries:
            cancelled = engine.cancel(engine_request_id) or cancelled
        return cancelled

    # -- fallback (kill switch) ---------------------------------------
    def _fallback_model(self, family: str):
        """(params, cfg) through the multiplex LRU wrapper — same
        bound and same per-family load serialization as the engine
        path (a hand-rolled dict would grow unboundedly and a single
        load lock would stall warm families behind a cold load)."""
        wrapper = self._fallback_wrapper
        if wrapper is None:
            from ..serve.multiplex import _ModelMultiplexWrapper

            with self._fallback_lock:
                if self._fallback_wrapper is None:
                    self._fallback_wrapper = _ModelMultiplexWrapper(
                        lambda owner, fam: build_model(
                            owner._spec(fam)
                        ),
                        self,
                        MAX_FAMILIES_PER_REPLICA,
                    )
                wrapper = self._fallback_wrapper
        return wrapper.load(family)

    def _serve_fallback(self, family, prompt, max_new, eos):
        """Per-request `generate_stream` — no shared cache, no
        batching: what serving looked like before the engine, kept as
        the RT_serve_engine_enabled=0 escape hatch and the servebench
        baseline."""
        import jax.numpy as jnp

        from ..models.generate import generate_stream
        from .kv_slots import bucket_for

        params, cfg = self._fallback_model(family)
        ec = self._engine_cfg
        max_new = int(
            ec.max_new_tokens if max_new is None else max_new
        )
        # Same length-bucket padding as the engine, so the baseline
        # pays the same bounded compile set, not one compile per
        # distinct prompt length.
        prompt = [int(t) for t in prompt]
        bucket = bucket_for(
            len(prompt), ec.prefill_chunk, ec.max_len
        )
        if len(prompt) + max_new > ec.max_len:
            # Same admission contract as the engine path: the kill
            # switch changes throughput, not validation semantics.
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new}) exceeds slot capacity "
                f"max_len={ec.max_len}"
            )
        padded = prompt + [0] * (bucket - len(prompt))
        for step_tokens in generate_stream(
            params,
            jnp.asarray([padded], jnp.int32),
            jnp.asarray([len(prompt)], jnp.int32),
            cfg,
            max_new_tokens=max_new,
            temperature=ec.temperature,
            top_k=ec.top_k,
            eos_token=ec.eos_token if eos is None else eos,
            # Fixed cache size: one compile per prompt bucket, same
            # as the engine, instead of one per (bucket, budget).
            cache_len=ec.max_len,
        ):
            yield f"{int(step_tokens[0])} ".encode()

    # -- introspection -------------------------------------------------
    def engine_stats(self) -> Dict[str, Any]:
        """Per-loaded-family engine stats for this replica (also the
        smoke-bench's concurrency witness)."""
        wrapper = getattr(self, "__serve_multiplex_get_engine", None)
        if wrapper is None:
            return {}
        return {
            family: engine.stats()
            for family, engine in wrapper.models().items()
        }


def build_llm_app(
    families: Dict[str, Dict[str, Any]],
    *,
    default_family: Optional[str] = None,
    engine: Optional[Dict[str, Any]] = None,
    engine_enabled: Optional[bool] = None,
    num_replicas: int = 1,
    max_ongoing_requests: Optional[int] = None,
    name: str = "llm",
):
    """Bind the engine deployment. `engine_enabled=None` resolves the
    RT_serve_engine_enabled kill switch HERE (driver-side) so the
    decision ships in the replica init args instead of depending on
    worker-process environments."""
    from .._private.config import Config
    from ..serve.deployment import deployment as serve_deployment

    runtime_cfg = Config.from_env()
    if engine_enabled is None:
        engine_enabled = runtime_cfg.serve_engine_enabled
    engine = dict(engine or {})
    if "prefix_cache" not in engine:
        # Same driver-side resolution as the engine kill switch: the
        # decision ships in the replica init args instead of depending
        # on worker-process environments.
        engine["prefix_cache"] = bool(
            runtime_cfg.serve_prefix_cache_enabled
        )
    engine_cfg = EngineConfig(**engine)
    if max_ongoing_requests is None:
        # Streams hold a replica thread for their whole lifetime:
        # admit enough for every slot plus a queueing margin so the
        # engine's FIFO — not the actor mailbox — orders waiters.
        max_ongoing_requests = engine_cfg.slots * 4
    dep = serve_deployment(
        name=name,
        num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
    )(LLMServer)
    return dep.bind(
        dict(families),
        default_family=default_family,
        engine=dict(engine or {}),
        engine_enabled=bool(engine_enabled),
    )
