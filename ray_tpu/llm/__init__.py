"""LLM inference serving: continuous-batching engine + serve glue.

The engine (engine.py) owns a PAGED KV cache — a refcounted block
pool with prefix reuse (kv_slots.py) — fed by a FIFO slot scheduler
gated on block availability (scheduler.py); serving.py wires it
behind `ray_tpu.serve` as a multiplexed streaming deployment, and
`servebench.py` at the repo root drives it with open-loop Poisson
traffic, single- and multi-replica (results in SERVEBENCH.json).
"""

from .engine import (
    BatchProgram,
    EngineConfig,
    EngineDead,
    EngineOverloaded,
    InferenceEngine,
    PolicyTicket,
    TokenStream,
)
from .scheduler import SlotScheduler
from .kv_slots import BlockAllocator, BlocksExhausted, PagedKVCache
from .serving import LLMServer, build_llm_app

__all__ = [
    "BatchProgram",
    "EngineConfig",
    "EngineDead",
    "EngineOverloaded",
    "InferenceEngine",
    "PolicyTicket",
    "TokenStream",
    "SlotScheduler",
    "BlockAllocator",
    "BlocksExhausted",
    "PagedKVCache",
    "LLMServer",
    "build_llm_app",
]
