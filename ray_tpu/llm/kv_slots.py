"""Slot-arranged KV cache for the continuous-batching engine.

Layout: one shared cache per engine, shaped

    k, v: [layers, n_slots, kv_heads, max_len, head_dim]

i.e. `models/generate.init_kv_cache` with batch == n_slots. Every
shape is STATIC: the decode step always runs over the full slot batch
(dead slots ride along masked by `alive`/`valid_len`), prompts pad to
a small set of length buckets, and prefill feeds fixed-size chunks —
so XLA compiles once per bucket and never again, the TPU-serving
contract (ISSUE: "static shapes so XLA compiles once per bucket").

Eviction is free-list bookkeeping only: a finished/cancelled slot is
NOT zeroed. Junk KV beyond a row's `valid_len` is masked out of
attention, and every position < valid_len is rewritten by the
occupying request before it becomes visible (prefill overwrites
[0, bucket); decode writes position p in the same step that extends
valid_len past p) — so reuse is O(1).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence

import jax
import jax.numpy as jnp

from ..models.llama import LlamaConfig
from ..models.generate import init_kv_cache


def bucket_for(n: int, chunk: int, max_len: int) -> int:
    """Smallest multiple of `chunk` holding `n` tokens (whole-chunk
    prefill: the last chunk pads rather than shrinking, keeping the
    chunk shape static). Raises when it exceeds the slot capacity."""
    if n < 1:
        raise ValueError("empty prompt")
    bucket = ((n + chunk - 1) // chunk) * chunk
    if bucket > max_len:
        raise ValueError(
            f"prompt of {n} tokens needs a {bucket}-token bucket but "
            f"slots hold max_len={max_len}"
        )
    return bucket


def _insert_slot_impl(cache_k, cache_v, new_k, new_v, slot):
    start = (0, slot, 0, 0, 0)
    cache_k = jax.lax.dynamic_update_slice(
        cache_k, new_k.astype(cache_k.dtype), start
    )
    cache_v = jax.lax.dynamic_update_slice(
        cache_v, new_v.astype(cache_v.dtype), start
    )
    return cache_k, cache_v


_insert_jit = None


def _insert_slot(cache_k, cache_v, new_k, new_v, slot):
    """Write a prefilled [layers, 1, heads, bucket, hd] region into
    slot `slot` at positions [0, bucket). `slot` is traced, so this
    compiles once per bucket length, not per slot. The big cache is
    donated on accelerator backends (in-place slot write, no
    whole-cache copy per admission); CPU keeps copies
    (models/generate.accel_donate)."""
    global _insert_jit
    if _insert_jit is None:
        from ..models.generate import accel_donate

        _insert_jit = partial(
            jax.jit, donate_argnums=accel_donate(0, 1)
        )(_insert_slot_impl)
    return _insert_jit(cache_k, cache_v, new_k, new_v, slot)


class SlotKVCache:
    """The engine's shared KV cache plus its prompt-length buckets."""

    def __init__(
        self,
        cfg: LlamaConfig,
        n_slots: int,
        max_len: int,
        prefill_chunk: int,
    ):
        if prefill_chunk < 1 or prefill_chunk > max_len:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} outside [1, {max_len}]"
            )
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.max_len = int(max_len)
        self.prefill_chunk = int(prefill_chunk)
        self._cache = init_kv_cache(cfg, self.n_slots, self.max_len)

    # -- decode-batch view --------------------------------------------
    @property
    def cache(self) -> Dict[str, jax.Array]:
        """The {"k", "v", "length"} dict the shared decode step
        consumes (models/generate._forward_with_cache layout)."""
        return self._cache

    @cache.setter
    def cache(self, new: Dict[str, jax.Array]) -> None:
        self._cache = new

    # -- prompt buckets ------------------------------------------------
    def bucket_for(self, prompt_len: int) -> int:
        return bucket_for(prompt_len, self.prefill_chunk, self.max_len)

    def fresh_prompt_cache(self, bucket: int) -> Dict[str, jax.Array]:
        """A batch-1 scratch cache for one request's chunked prefill;
        inserted into the slot batch on completion."""
        return init_kv_cache(self.cfg, 1, bucket)

    def insert(
        self, slot: int, prompt_cache: Dict[str, jax.Array]
    ) -> None:
        """Adopt a completed prefill into slot `slot`."""
        self._cache["k"], self._cache["v"] = _insert_slot(
            self._cache["k"],
            self._cache["v"],
            prompt_cache["k"],
            prompt_cache["v"],
            jnp.int32(slot),
        )

    def nbytes(self) -> int:
        return int(self._cache["k"].nbytes + self._cache["v"].nbytes)
