"""Paged KV cache for the continuous-batching engine.

PR 10's fixed slot arenas shaped [layers, slots, kv_heads, max_len,
head_dim] made every request — a 6-token chat turn included — reserve
`max_len` positions of KV for its whole lifetime, so slot count (the
decode batch width) was hard-coupled to worst-case sequence memory.
This module replaces them with a PAGED cache (ISSUE 11 / ROADMAP
item 1c):

* one shared pool of `block_len`-sized KV blocks,

      k, v: [layers, n_blocks, kv_heads, block_len, head_dim]

  (models/generate.init_block_pool);
* a per-request PAGE TABLE mapping logical block j -> physical block
  id; attention gathers blocks back into logical order per step
  (models/generate._paged_layer), so the math — and the greedy token
  stream — is identical to the contiguous cache;
* a refcounted `BlockAllocator` (the plasma-style ownership model of
  the reference object plane: pin/refcount, free-list reuse, nothing
  zeroed) with PREFIX CACHING: full prompt blocks register under the
  exact token prefix they hold, and a later request whose prompt
  starts with the same tokens shares those blocks — its prefill
  SKIPS them entirely (shared system prompts become nearly free).

Shapes stay STATIC: the decode step runs over the full slot batch
with full-width [slots, max_blocks] tables (dead rows ride along
pointing at the reserved null block 0), prompts pad to prefill-chunk
buckets, and chunks are fixed-size — XLA compiles the paged prefill
and decode step each ONCE per engine geometry (the per-bucket scratch
caches of the arena design are gone).

Junk-is-masked contract (unchanged from the arenas): a freed block is
NOT zeroed. Attention masks positions >= valid_len, and every visible
position is rewritten by its owning request before valid_len covers
it — so alloc/free is pure host bookkeeping, O(1) per block.

Immutability contract for shared blocks: only FULL blocks of prompt
tokens register in the prefix table, decode writes always land at
positions >= len(prompt) (never inside a full prompt block), and a
registered block is only ever written again after eviction
unregisters it — so a cache hit can never observe a block mid-rewrite.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Hashable, List, Sequence

from ..models.llama import LlamaConfig
from ..models.generate import init_block_pool


def bucket_for(n: int, chunk: int, max_len: int) -> int:
    """Smallest multiple of `chunk` holding `n` tokens (whole-chunk
    prefill: the last chunk pads rather than shrinking, keeping the
    chunk shape static). Raises when it exceeds the per-request
    capacity."""
    if n < 1:
        raise ValueError("empty prompt")
    bucket = ((n + chunk - 1) // chunk) * chunk
    if bucket > max_len:
        raise ValueError(
            f"prompt of {n} tokens needs a {bucket}-token bucket but "
            f"requests are capped at max_len={max_len}"
        )
    return bucket


def default_block_len(prefill_chunk: int, cap: int = 16) -> int:
    """Auto block length: the largest divisor of the prefill chunk at
    most `cap` — chunks must cover whole blocks so a chunked prefill
    never splits a block write across dispatches."""
    for cand in range(min(cap, prefill_chunk), 0, -1):
        if prefill_chunk % cand == 0:
            return cand
    return 1


class BlocksExhausted(RuntimeError):
    """The pool has fewer free (or evictable cached) blocks than the
    reservation needs."""


#: The reserved scratch block every dead slot's table points at; its
#: contents are garbage by design and never gathered for a live row.
NULL_BLOCK = 0


class BlockAllocator:
    """Refcounted physical-block bookkeeping plus the prefix-reuse
    table. Pure host-side Python — no JAX — so its invariants are
    unit-testable in microseconds (tests/test_kv_blocks.py).

    Block states:

    * free        — on the free list, contents meaningless;
    * pinned      — refcount >= 1, owned by one or more live requests
                    (shared only via a prefix-cache hit);
    * cached-free — refcount 0 but still registered under its prompt
                    prefix in an LRU: reusable by a future prefix hit,
                    evictable (oldest first) when a reservation
                    outgrows the free list.

    Prefix keys are opaque hashables minted by the cache owner
    (PagedKVCache chains SHA-256 digests over the token prefix a
    block completes — O(prompt) to build, and a cross-prompt
    collision would be a SHA-256 collision).
    """

    def __init__(self, n_blocks: int, reserved: int = 1):
        if n_blocks <= reserved:
            raise ValueError(
                f"pool needs > {reserved} blocks, got {n_blocks}"
            )
        self.n_blocks = int(n_blocks)
        self.reserved = int(reserved)
        # LIFO free list: a just-freed (cache-warm) block is reused
        # first, same as the arena slot free list.
        self._free: List[int] = list(
            range(n_blocks - 1, reserved - 1, -1)
        )
        self._refcount: Dict[int, int] = {}
        self._prefix_to_block: Dict[Hashable, int] = {}
        self._block_prefix: Dict[int, Hashable] = {}
        #: refcount-0 blocks still holding a registered prefix, oldest
        #: first (eviction order).
        self._cached: "OrderedDict[int, None]" = OrderedDict()

    # -- capacity ------------------------------------------------------
    def capacity(self) -> int:
        """Blocks a single reservation could ever obtain."""
        return self.n_blocks - self.reserved

    def available(self) -> int:
        """Blocks obtainable right now (free + evictable cached)."""
        return len(self._free) + len(self._cached)

    def used(self) -> int:
        """Blocks pinned by live requests."""
        return len(self._refcount)

    def cached(self) -> int:
        """Refcount-0 blocks retained for prefix reuse."""
        return len(self._cached)

    # -- allocation ----------------------------------------------------
    def reserve(self, n: int) -> List[int]:
        """Claim `n` blocks at refcount 1. Free blocks first, then
        LRU-evict cached-free blocks (their prefix entries drop).
        Raises BlocksExhausted — the caller sheds or keeps the request
        queued — without handing out a partial set."""
        if n < 0:
            raise ValueError(f"reserve({n})")
        if n > self.available():
            raise BlocksExhausted(
                f"need {n} KV blocks, only {self.available()} "
                f"available (pool {self.capacity()})"
            )
        out: List[int] = []
        for _ in range(n):
            if self._free:
                block = self._free.pop()
            else:
                block, _ = self._cached.popitem(last=False)
                del self._prefix_to_block[self._block_prefix.pop(block)]
            self._refcount[block] = 1
            out.append(block)
        return out

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block. A block reaching refcount 0
        goes to the cached-free LRU if it still holds a registered
        prefix, else back to the free list. Double-free raises — the
        engine-killing class of bug the arena design hit once
        (PR 10's mid-prefill cancel) must be loud here too."""
        for block in blocks:
            count = self._refcount.get(block)
            if count is None:
                raise ValueError(
                    f"double free of KV block {block}"
                )
            if count > 1:
                self._refcount[block] = count - 1
                continue
            del self._refcount[block]
            if block in self._block_prefix:
                self._cached[block] = None
            else:
                self._free.append(block)

    # -- prefix cache --------------------------------------------------
    def peek_prefix(self, keys: Sequence[Hashable]) -> int:
        """Length of the longest cached run of `keys` (no pinning) —
        the admission gate's lookahead."""
        hits = 0
        for key in keys:
            if key not in self._prefix_to_block:
                break
            hits += 1
        return hits

    def peek_cached(self, keys: Sequence[Hashable], limit: int) -> int:
        """Among the first `limit` blocks of the longest cached run of
        `keys`, how many are currently refcount-0 (cached-free)?
        Pinning THOSE removes them from `available()`; hit blocks
        already pinned by a live request cost nothing to share — the
        distinction the admission gate needs to budget a reservation
        exactly (no pinning here)."""
        cached = 0
        for key in keys[: max(0, limit)]:
            block = self._prefix_to_block.get(key)
            if block is None:
                break
            if self._refcount.get(block, 0) == 0:
                cached += 1
        return cached

    def match_prefix(self, keys: Sequence[Hashable]) -> List[int]:
        """Pin and return the blocks of the longest cached run of
        `keys`. Pinning removes a cached-free block from the eviction
        LRU, so a reservation made after this call cannot steal a
        matched block."""
        out: List[int] = []
        for key in keys:
            block = self._prefix_to_block.get(key)
            if block is None:
                break
            count = self._refcount.get(block, 0)
            if count == 0:
                self._cached.pop(block, None)
            self._refcount[block] = count + 1
            out.append(block)
        return out

    def register(self, block: int, key: Hashable) -> bool:
        """Publish a pinned block as the cache of prompt prefix `key`.
        First writer wins: if the prefix (or the block) is already
        registered the call is a no-op — the caller's copy simply
        stays private."""
        if self._refcount.get(block) is None:
            raise ValueError(
                f"register of unpinned KV block {block}"
            )
        if key in self._prefix_to_block or block in self._block_prefix:
            return False
        self._prefix_to_block[key] = block
        self._block_prefix[block] = key
        return True

    def stats(self) -> Dict[str, int]:
        return {
            "kv_blocks_total": self.capacity(),
            "kv_blocks_used": self.used(),
            "kv_blocks_cached": self.cached(),
            "kv_blocks_free": len(self._free),
        }


class PagedKVCache:
    """The engine's shared block pool plus its geometry: block length,
    per-request logical-table width, and prompt-length buckets."""

    def __init__(
        self,
        cfg: LlamaConfig,
        n_blocks: int,
        block_len: int,
        max_len: int,
        prefill_chunk: int,
    ):
        if prefill_chunk < 1 or prefill_chunk > max_len:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} outside [1, {max_len}]"
            )
        if block_len < 1 or prefill_chunk % block_len != 0:
            raise ValueError(
                f"kv_block_len {block_len} must divide the prefill "
                f"chunk {prefill_chunk} (chunks write whole blocks)"
            )
        if max_len % block_len != 0:
            raise ValueError(
                f"max_len {max_len} must be a multiple of "
                f"kv_block_len {block_len}"
            )
        self.cfg = cfg
        self.block_len = int(block_len)
        self.max_len = int(max_len)
        self.prefill_chunk = int(prefill_chunk)
        #: Logical table width: the block count a max_len sequence
        #: needs; every request's table pads to it (static shapes).
        self.max_blocks = self.max_len // self.block_len
        self.alloc = BlockAllocator(n_blocks, reserved=1)
        self._pool = init_block_pool(cfg, int(n_blocks), self.block_len)

    # -- pool ----------------------------------------------------------
    @property
    def pool(self) -> Dict[str, object]:
        """The {"k", "v"} block pool the jitted paged kernels consume
        and (on accelerator backends, via donation) update in place."""
        return self._pool

    @pool.setter
    def pool(self, new: Dict[str, object]) -> None:
        self._pool = new

    # -- geometry ------------------------------------------------------
    def bucket_for(self, prompt_len: int) -> int:
        return bucket_for(prompt_len, self.prefill_chunk, self.max_len)

    def blocks_for(self, total_tokens: int) -> int:
        """Blocks a sequence of `total_tokens` positions occupies."""
        return -(-int(total_tokens) // self.block_len)

    def prefix_keys(self, prompt: Sequence[int]) -> List[bytes]:
        """Prefix-cache keys for every FULL block of `prompt`: key i
        is an incremental SHA-256 chain digest(i-1) || block-i tokens,
        so building all keys is O(prompt_len) in time AND memory
        (materializing the exact prefix per key would be quadratic),
        while the chain still binds each key to the ENTIRE token
        prefix — a cross-prompt key collision is a SHA-256 collision.
        The final PARTIAL block (if any) never gets a key — decode
        writes into it, and shared blocks must stay immutable."""
        import hashlib

        bl = self.block_len
        keys: List[bytes] = []
        digest = b"rt-paged-kv-prefix"
        for i in range(len(prompt) // bl):
            chained = hashlib.sha256(digest)
            chained.update(
                ",".join(map(str, prompt[i * bl:(i + 1) * bl])).encode()
            )
            digest = chained.digest()
            keys.append(digest)
        return keys

    def nbytes(self) -> int:
        return int(
            self._pool["k"].nbytes + self._pool["v"].nbytes
        )
