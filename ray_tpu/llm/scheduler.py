"""Slot scheduler for the continuous-batching engine.

Pure bookkeeping — no JAX, no threads — so its invariants are unit-
testable in microseconds (tests/test_llm_engine.py). One scheduler
manages ONE engine's slots (one model family; multiplexed families
each get their own engine and therefore their own scheduler — that is
the per-family slot accounting).

Policy:

* Admission is strict FIFO over the waiting queue. A request is
  admitted the moment a slot is free and it is at the head — a
  long-prompt request can never be starved by short ones arriving
  behind it (its prefill cost is bounded per engine iteration by
  chunking, not by skipping it).
* Slots are a free LIST (LIFO reuse): a freed slot is handed to the
  next admission immediately — eviction of a finished/cancelled
  sequence frees capacity in the SAME engine iteration.
* The waiting queue is bounded (`max_waiting`); past the bound,
  `submit` raises `EngineOverloaded` so the serve layer sheds load
  with an error instead of queueing unboundedly (the router/proxy
  admission story: the proxy 503s on connection floods, the engine
  rejects when its own queue is full).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple


class EngineOverloaded(RuntimeError):
    """The engine's waiting queue is full; retry later."""


class EngineDead(RuntimeError):
    """The engine's step loop died or was shut down; the original
    failure (if any) is the __cause__."""


class SlotScheduler:
    """Slot accounting + FIFO admission for one engine."""

    def __init__(self, n_slots: int, max_waiting: int = 256):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.n_slots = int(n_slots)
        self.max_waiting = int(max_waiting)
        self._free: List[int] = list(range(n_slots - 1, -1, -1))
        self._waiting: Deque[Any] = deque()
        self._running: Dict[int, Any] = {}  # slot -> request

    # -- admission -----------------------------------------------------
    def submit(self, request: Any) -> None:
        if len(self._waiting) >= self.max_waiting:
            raise EngineOverloaded(
                f"engine waiting queue full ({self.max_waiting}); "
                "shed or retry"
            )
        self._waiting.append(request)

    def admit_next(self, gate=None) -> Optional[Tuple[Any, int]]:
        """Pop the FIFO head into a free slot; None when nothing can
        be admitted (no waiters or no free slot). `gate(request) ->
        bool` may veto the head — the paged engine gates on KV-block
        availability — and a vetoed head STAYS the head: admission
        remains strict FIFO (no skip-ahead), so a big request waits
        for blocks instead of being starved by smaller ones."""
        if not self._waiting or not self._free:
            return None
        request = self._waiting[0]
        if gate is not None and not gate(request):
            return None
        slot = self._free.pop()
        self._waiting.popleft()
        self._running[slot] = request
        return request, slot

    # -- release -------------------------------------------------------
    def release(self, slot: int) -> Any:
        """Free a running slot (finish/cancel/error); returns the
        request that held it."""
        request = self._running.pop(slot)
        self._free.append(slot)
        return request

    def remove_waiting(self, request: Any) -> bool:
        """Drop a not-yet-admitted request (cancellation while
        queued)."""
        try:
            self._waiting.remove(request)
            return True
        except ValueError:
            return False

    def drain(self) -> List[Any]:
        """Remove every request (shutdown/death); returns them all."""
        doomed = list(self._waiting) + list(self._running.values())
        self._waiting.clear()
        for slot in list(self._running):
            self.release(slot)
        return doomed

    # -- views ---------------------------------------------------------
    @property
    def running(self) -> Dict[int, Any]:
        return self._running

    @property
    def waiting(self) -> Deque[Any]:
        return self._waiting

    def stats(self) -> Dict[str, int]:
        return {
            "slots_total": self.n_slots,
            "slots_used": len(self._running),
            "waiting": len(self._waiting),
        }
