"""Environments.

Reference: RLlib consumes Farama-gymnasium envs (rllib/env/). The
framework ships a dependency-free numpy CartPole (standard dynamics,
the classic control benchmark RLlib's smoke tests train on) plus a
vectorized wrapper matching the gymnasium reset/step contract.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class CartPoleEnv:
    """CartPole-v1 dynamics (standard published constants)."""

    observation_size = 4
    num_actions = 2
    max_episode_steps = 500

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.length = 0.5  # half-pole
        self.force_mag = 10.0
        self.tau = 0.02
        self.x_threshold = 2.4
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.state: Optional[np.ndarray] = None
        self._steps = 0

    def reset(self) -> np.ndarray:
        self.state = self._rng.uniform(-0.05, 0.05, size=4).astype(
            np.float32
        )
        self._steps = 0
        return self.state.copy()

    def step(
        self, action: int
    ) -> Tuple[np.ndarray, float, bool, bool, Dict[str, Any]]:
        assert self.state is not None, "call reset() first"
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (
            force + polemass_length * theta_dot**2 * sintheta
        ) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length
            * (4.0 / 3.0 - self.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self.state = np.array(
            [x, x_dot, theta, theta_dot], dtype=np.float32
        )
        self._steps += 1
        terminated = bool(
            x < -self.x_threshold
            or x > self.x_threshold
            or theta < -self.theta_threshold
            or theta > self.theta_threshold
        )
        truncated = self._steps >= self.max_episode_steps
        return self.state.copy(), 1.0, terminated, truncated, {}


class VectorEnv:
    """N independent envs stepped together with auto-reset
    (reference: gymnasium SyncVectorEnv semantics used by
    SingleAgentEnvRunner)."""

    def __init__(self, make_env, num_envs: int, seed: int = 0):
        self.envs = [make_env(seed + i) for i in range(num_envs)]
        self.num_envs = num_envs

    def reset(self) -> np.ndarray:
        return np.stack([env.reset() for env in self.envs])

    def step(self, actions: np.ndarray):
        obs, rewards, terminateds, truncateds = [], [], [], []
        for env, action in zip(self.envs, actions):
            o, r, term, trunc, _ = env.step(int(action))
            if term or trunc:
                o = env.reset()
            obs.append(o)
            rewards.append(r)
            terminateds.append(term)
            truncateds.append(trunc)
        return (
            np.stack(obs),
            np.asarray(rewards, dtype=np.float32),
            np.asarray(terminateds),
            np.asarray(truncateds),
        )


ENV_REGISTRY = {"CartPole-v1": CartPoleEnv}


def make_env(name_or_cls, seed: int = 0):
    if isinstance(name_or_cls, str):
        cls = ENV_REGISTRY[name_or_cls]
    else:
        cls = name_or_cls
    return cls(seed=seed)
