"""Versioned, drainless policy-weight synchronization (ISSUE 13).

Two halves, one version counter:

* **WeightStore** — an actor publishing ``(version, wrapped ref)`` of
  the learner's latest params. PULL side of the sync: env runners
  doing LOCAL policy inference poll ``latest_version()`` (an int —
  cheap) between fragments and fetch the ref only when it moved; the
  payload rides the object store (zero-copy on one host), never this
  actor.
* **push_weights** — the PUSH side: one `rt.put` of the params, then
  a concurrent fan-out to every inference engine's
  ``update_weights`` (the ISSUE 13 engine API: in-flight requests
  finish token-exact on the old generation, the next admission
  serves the new one — the engine is never drained), plus the store
  publish and the rollout queue's ``set_learner_version`` (which
  arms the staleness gates). Returns the end-to-end latency — the
  ``weight_sync_ms`` series rlbench commits and the learner bills as
  a first-class stall phase next to data_wait.

The version counter is owned by the caller (the learner loop): it
increments per publish, tags every fragment the runners produce, and
its gap to the queue's learner version IS the weight lag —
``rl_weight_version`` / ``rl_weight_lag`` gauges on /metrics.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["WeightStore", "push_weights", "observe_weight_lag"]


class WeightStore:
    """Actor body: versioned weight publication for pull-side sync.
    Weights are held as a WRAPPED object-store ref (``[ref]``) so the
    store never materializes the payload; `get()` hands the wrapper
    back and the runner resolves it straight from the store."""

    def __init__(self, name: str = "policy"):
        from collections import deque

        self._name = name
        self._version = 0
        self._item: Optional[list] = None
        # Superseded wrappers retained briefly: a runner's get()
        # reply may still be in flight when the next publish lands —
        # dropping the old wrapper immediately would race the
        # reply's borrow registration and free the params mid-fetch.
        self._old: "deque" = deque(maxlen=4)
        self._publishes = 0

    def publish(self, item: list, version: int) -> int:
        """Install `item` (a wrapped ref ``[ref]``) as `version`.
        Stale publishes (version <= current) are ignored — a late
        retry must never roll weights back."""
        version = int(version)
        if version > self._version:
            if self._item is not None:
                self._old.append(self._item)
            self._item = item
            self._version = version
            self._publishes += 1
            self._observe()
        return self._version

    def latest_version(self) -> int:
        return self._version

    def get(self, min_version: int = 0):
        """(version, wrapped ref) of the latest weights; the wrapper
        is ``None`` until the first publish. `min_version` is advisory
        (callers poll; the store never blocks)."""
        return self._version, self._item

    def ping(self) -> str:
        return "ok"

    def stats(self) -> Dict[str, Any]:
        return {
            "version": self._version,
            "publishes": self._publishes,
            "has_weights": self._item is not None,
        }

    def _observe(self) -> None:
        try:
            from ..util.metrics import Gauge

            global _STORE_GAUGE
            if _STORE_GAUGE is None:
                _STORE_GAUGE = Gauge(
                    "rl_weight_version",
                    description=(
                        "Latest policy-weight version published by "
                        "the learner"
                    ),
                    tag_keys=("store",),
                )
            _STORE_GAUGE.set(
                float(self._version), tags={"store": self._name}
            )
        except Exception:
            pass


_STORE_GAUGE = None
_LAG_GAUGE = None
_SYNC_HIST = None


def observe_weight_lag(lag: float, *, role: str = "runner") -> None:
    """Publish the observed weight lag (learner version minus the
    version actually generating/serving rollouts) as the
    ``rl_weight_lag`` gauge — the /metrics half of the
    ``max_weight_lag`` contract."""
    try:
        from ..util.metrics import Gauge

        global _LAG_GAUGE
        if _LAG_GAUGE is None:
            _LAG_GAUGE = Gauge(
                "rl_weight_lag",
                description=(
                    "Weight-version lag between the learner and the "
                    "policy generating rollouts"
                ),
                tag_keys=("role",),
            )
        _LAG_GAUGE.set(float(lag), tags={"role": role})
    except Exception:
        pass


def push_weights(
    params: Any,
    version: int,
    *,
    engines: Sequence[Any] = (),
    store: Optional[Any] = None,
    queue: Optional[Any] = None,
    timeout: float = 60.0,
) -> float:
    """One drainless weight sync: put the params ONCE, fan the ref out
    concurrently to every engine (`update_weights`), the weight store
    and the rollout queue, and wait for all acks. Returns wall ms —
    the committed ``rl_weight_sync_ms`` number.

    The engines receive the ref TOP-LEVEL (materialized engine-side
    from the store, one zero-copy read each); the store/queue receive
    it WRAPPED (version bookkeeping only, no payload)."""
    import ray_tpu as rt

    t0 = time.perf_counter()
    ref = rt.put(params)
    acks: List[Any] = []
    for engine in engines:
        acks.append(
            engine.update_weights.remote(ref, version=int(version))
        )
    if store is not None:
        acks.append(store.publish.remote([ref], int(version)))
    if queue is not None:
        acks.append(
            queue.set_learner_version.remote(int(version))
        )
    if acks:
        rt.get(acks, timeout=timeout)
    ms = (time.perf_counter() - t0) * 1e3
    try:
        from ..util.metrics import Histogram

        global _SYNC_HIST
        if _SYNC_HIST is None:
            _SYNC_HIST = Histogram(
                "rl_weight_sync_ms",
                description=(
                    "End-to-end drainless weight push: put + engine/"
                    "store/queue fan-out + acks"
                ),
                boundaries=(
                    1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                    500.0, 1000.0, 2500.0,
                ),
                tag_keys=(),
            )
        _SYNC_HIST.observe(ms)
    except Exception:
        pass
    return ms
