"""Fault-tolerant actor management for RL worker fleets.

Reference: rllib/utils/actor_manager.py:198 FaultTolerantActorManager —
fan a method call across a set of actors, tolerate individual failures
(mark unhealthy instead of raising), and restore actors on a later
probe. RLlib wraps gRPC actor calls; here failures surface as
ActorDiedError/ActorUnavailableError/RpcError from the runtime and the
manager recreates dead actors from a factory, so a killed env-runner
costs one sample's worth of data, never the training iteration.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


@dataclass
class CallResult:
    """One actor's result or error (reference: ResultOrError)."""

    actor_id: int
    ok: bool
    value: Any = None
    error: Optional[BaseException] = None


class FaultTolerantActorManager:
    """Owns a fleet of actors indexed by small integer ids.

    `actor_factory(actor_id)` builds a replacement when a slot's actor
    is found dead; `on_restore(actor_id, handle)` re-initializes it
    (e.g. re-sync policy weights) before it rejoins the healthy set.
    """

    def __init__(
        self,
        actors: List[Any],
        *,
        actor_factory: Optional[Callable[[int], Any]] = None,
        on_restore: Optional[Callable[[int, Any], None]] = None,
        max_remote_requests_in_flight: int = 2,
    ):
        self._actors: Dict[int, Any] = dict(enumerate(actors))
        self._healthy: Dict[int, bool] = {
            idx: True for idx in self._actors
        }
        self._factory = actor_factory
        self._on_restore = on_restore
        self.max_remote_requests_in_flight = (
            max_remote_requests_in_flight
        )

    # -- introspection -------------------------------------------------
    def num_actors(self) -> int:
        return len(self._actors)

    def num_healthy_actors(self) -> int:
        return sum(1 for ok in self._healthy.values() if ok)

    def healthy_actor_ids(self) -> List[int]:
        return [idx for idx, ok in self._healthy.items() if ok]

    def actor(self, actor_id: int) -> Any:
        return self._actors[actor_id]

    # -- fan-out -------------------------------------------------------
    def foreach_actor(
        self,
        method: str,
        *args,
        healthy_only: bool = True,
        timeout: float = 120.0,
        mark_unhealthy_on_failure: bool = True,
        **kwargs,
    ) -> List[CallResult]:
        """Call `method` on every (healthy) actor; per-actor failures
        become CallResult(ok=False) and the actor is marked unhealthy
        (reference: foreach_actor + ResultOrError, never raising for a
        single lost worker)."""
        import ray_tpu as rt

        targets = [
            idx
            for idx in sorted(self._actors)
            if not healthy_only or self._healthy[idx]
        ]
        refs = {}
        results: List[CallResult] = []
        for idx in targets:
            try:
                refs[idx] = getattr(
                    self._actors[idx], method
                ).remote(*args, **kwargs)
            except Exception as e:  # submit-side failure
                results.append(
                    CallResult(actor_id=idx, ok=False, error=e)
                )
                if mark_unhealthy_on_failure:
                    self._healthy[idx] = False
        # CONCURRENT gather (ISSUE 13 satellite): one rt.wait over
        # the whole fan-out instead of serial per-ref round-trips —
        # N healthy actors complete in one pass and a single dead
        # actor costs the remaining budget ONCE, not once per
        # still-pending ref behind it. ONE deadline bounds the pass
        # (sequential timeouts would compound: 3 hung actors = 3x
        # the budget), matching the reference manager's contract.
        import time as _time

        deadline = _time.monotonic() + timeout
        pending = dict(refs)
        while pending:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                break
            ready, _ = rt.wait(
                list(pending.values()),
                num_returns=len(pending),
                timeout=remaining,
            )
            ready_set = set(ready)
            drained = [
                idx for idx, ref in pending.items()
                if ref in ready_set
            ]
            if not drained:
                break  # deadline hit with nothing new
            for idx in drained:
                ref = pending.pop(idx)
                try:
                    value = rt.get(ref, timeout=5)
                    results.append(
                        CallResult(actor_id=idx, ok=True, value=value)
                    )
                except Exception as e:
                    results.append(
                        CallResult(actor_id=idx, ok=False, error=e)
                    )
                    if mark_unhealthy_on_failure:
                        self._healthy[idx] = False
        for idx, ref in pending.items():  # never completed: timeout
            results.append(
                CallResult(
                    actor_id=idx,
                    ok=False,
                    error=TimeoutError(
                        f"{method} on actor {idx} exceeded the "
                        f"{timeout}s fan-out deadline"
                    ),
                )
            )
            if mark_unhealthy_on_failure:
                self._healthy[idx] = False
        results.sort(key=lambda r: r.actor_id)
        return results

    def ok_values(self, results: List[CallResult]) -> List[Any]:
        return [r.value for r in results if r.ok]

    # -- health management --------------------------------------------
    def probe_unhealthy_actors(self, timeout: float = 30.0) -> List[int]:
        """Ping unhealthy slots; replace dead actors from the factory
        and run on_restore on each comeback (reference:
        probe_unhealthy_actors + restored-actor sync). Returns the ids
        restored to the healthy set."""
        import ray_tpu as rt

        restored: List[int] = []
        for idx, ok in list(self._healthy.items()):
            if ok:
                continue
            actor = self._actors[idx]
            alive = False
            try:
                rt.get(actor.ping.remote(), timeout=timeout)
                alive = True
            except Exception:
                alive = False
            if not alive and self._factory is not None:
                try:
                    rt.kill(actor)
                except Exception:
                    pass
                actor = self._factory(idx)
                self._actors[idx] = actor
                try:
                    rt.get(actor.ping.remote(), timeout=timeout)
                    alive = True
                except Exception:
                    alive = False
            if alive:
                if self._on_restore is not None:
                    self._on_restore(idx, actor)
                self._healthy[idx] = True
                restored.append(idx)
        return restored

    def shutdown(self) -> None:
        import ray_tpu as rt

        for actor in self._actors.values():
            try:
                rt.kill(actor)
            except Exception:
                pass
        self._actors.clear()
        self._healthy.clear()
