"""Policy/value network.

Reference: rllib/core/rl_module/ — an RLModule owns the neural nets
for action distribution + value function. TPU-native form: a pure
functional jax MLP (params pytree + apply), so the same module runs
in env runners (CPU inference) and under pjit in the learner.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .._private import compile_watch


def init_policy_params(
    key, obs_size: int, num_actions: int, hidden: Tuple[int, ...] = (64, 64)
) -> Dict:
    sizes = (obs_size, *hidden)
    params = {"layers": [], "pi": None, "vf": None}
    keys = jax.random.split(key, len(hidden) + 2)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        w = jax.random.orthogonal(keys[i], max(fan_in, fan_out))[
            :fan_in, :fan_out
        ] * jnp.sqrt(2.0)
        params["layers"].append(
            {"w": w, "b": jnp.zeros((fan_out,))}
        )
    params["pi"] = {
        "w": jax.random.orthogonal(keys[-2], max(hidden[-1], num_actions))[
            :hidden[-1], :num_actions
        ]
        * 0.01,
        "b": jnp.zeros((num_actions,)),
    }
    params["vf"] = {
        "w": jax.random.orthogonal(keys[-1], hidden[-1])[:, :1],
        "b": jnp.zeros((1,)),
    }
    return params


def apply_policy(params: Dict, obs: jnp.ndarray):
    """obs [B, obs_size] -> (logits [B, A], value [B])."""
    x = obs
    for layer in params["layers"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return logits, value


@jax.jit
def _sample_jit(params, obs, key):
    logits, value = apply_policy(params, obs)
    key, sub = jax.random.split(key)
    actions = jax.random.categorical(sub, logits)
    logp = jax.nn.log_softmax(logits)[
        jnp.arange(logits.shape[0]), actions
    ]
    return actions, logp, value, key


_sample_jit = compile_watch.instrument("rl.sample_actions", _sample_jit)


def sample_actions(params: Dict, obs: np.ndarray, key):
    """Inference-side sampling used by env runners."""
    actions, logp, value, key = _sample_jit(
        params, jnp.asarray(obs), key
    )
    return (
        np.asarray(actions),
        np.asarray(logp),
        np.asarray(value),
        key,
    )
