"""JaxLearner: the gradient-update half of the algorithm.

Reference: rllib/core/learner/ — TorchLearner wraps DDP around
compute_gradients/apply_gradients (torch_learner.py:171,192) and
LearnerGroup fans batches across learner actors via Train's backend
executor (learner_group.py:81,167). TPU-native replacement: ONE
learner process whose jitted update spans the whole device mesh via
GSPMD (data-parallel minibatch sharding with psum'd gradients happens
inside XLA), so multi-chip scaling needs no actor-side gradient
plumbing. A multi-host LearnerGroup is the Train gang (JaxBackend
rendezvous) running this same learner under pjit.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .models import apply_policy, init_policy_params


class JaxLearner:
    def __init__(
        self,
        obs_size: int,
        num_actions: int,
        *,
        lr: float = 3e-4,
        clip_eps: float = 0.2,
        vf_coef: float = 0.5,
        entropy_coef: float = 0.01,
        minibatch_size: int = 256,
        num_epochs: int = 4,
        max_grad_norm: float = 0.5,
        hidden: Tuple[int, ...] = (64, 64),
        seed: int = 0,
    ):
        self.params = init_policy_params(
            jax.random.PRNGKey(seed), obs_size, num_actions, hidden
        )
        self.tx = optax.chain(
            optax.clip_by_global_norm(max_grad_norm),
            optax.adam(lr),
        )
        self.opt_state = self.tx.init(self.params)
        self.clip_eps = clip_eps
        self.vf_coef = vf_coef
        self.entropy_coef = entropy_coef
        self.minibatch_size = minibatch_size
        self.num_epochs = num_epochs
        self._rng = np.random.default_rng(seed)
        from .._private import compile_watch

        self._update_jit = compile_watch.instrument(
            "rl.ppo.minibatch_update", jax.jit(self._minibatch_update)
        )
        # Split-phase entry points for the multi-learner path
        # (learner_group.py): gradients computed per shard, applied
        # identically everywhere after averaging (reference:
        # learner.py compute_gradients/apply_gradients split,
        # torch_learner.py:171,192).
        self._grad_jit = compile_watch.instrument(
            "rl.ppo.compute_gradients",
            jax.jit(
                lambda params, batch: jax.value_and_grad(
                    self._loss, has_aux=True
                )(params, batch)
            ),
        )
        self._apply_jit = compile_watch.instrument(
            "rl.ppo.apply_gradients", jax.jit(self._apply_gradients)
        )

    # -- PPO loss (reference: ppo_torch_learner compute_loss) ---------
    def _loss(self, params, batch):
        logits, values = apply_policy(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1
        )[:, 0]
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        unclipped = ratio * adv
        clipped = (
            jnp.clip(ratio, 1 - self.clip_eps, 1 + self.clip_eps) * adv
        )
        policy_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
        vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jax.nn.softmax(logits) * logp_all, axis=1)
        )
        total = (
            policy_loss
            + self.vf_coef * vf_loss
            - self.entropy_coef * entropy
        )
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    def _minibatch_update(self, params, opt_state, batch):
        (loss, metrics), grads = jax.value_and_grad(
            self._loss, has_aux=True
        )(params, batch)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics, total_loss=loss)
        return params, opt_state, metrics

    def _apply_gradients(self, params, opt_state, grads):
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    # -- split-phase API (multi-learner) ------------------------------
    def compute_gradients(self, minibatch) -> Tuple[Dict, Dict]:
        """Gradients of the PPO loss on one (already device-ready)
        minibatch; params unchanged."""
        (loss, metrics), grads = self._grad_jit(self.params, minibatch)
        return grads, dict(metrics, total_loss=loss)

    def apply_gradients(self, grads) -> None:
        self.params, self.opt_state = self._apply_jit(
            self.params, self.opt_state, grads
        )

    # -- public --------------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """Epochs of shuffled minibatch SGD over one sample batch
        (reference: ppo.py training_step's learner update)."""
        n = len(batch["obs"])
        device_batch = {
            "obs": jnp.asarray(batch["obs"]),
            "actions": jnp.asarray(batch["actions"]),
            "logp": jnp.asarray(batch["logp"]),
            "advantages": jnp.asarray(batch["advantages"]),
            "value_targets": jnp.asarray(batch["value_targets"]),
        }
        metrics = {}
        for _ in range(self.num_epochs):
            perm = self._rng.permutation(n)
            for start in range(0, n, self.minibatch_size):
                idx = perm[start : start + self.minibatch_size]
                if len(idx) < self.minibatch_size and start > 0:
                    continue  # drop ragged tail (static jit shapes)
                minibatch = {
                    k: v[idx] for k, v in device_batch.items()
                }
                self.params, self.opt_state, metrics = (
                    self._update_jit(
                        self.params, self.opt_state, minibatch
                    )
                )
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        self.params = jax.device_put(params)
