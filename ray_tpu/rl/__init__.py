"""Reinforcement learning (reference: rllib/ new API stack —
EnvRunnerGroup + Learner + Algorithm)."""

from .env import ENV_REGISTRY, CartPoleEnv, VectorEnv, make_env
from .env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from .learner import JaxLearner
from .ppo import PPO, PPOConfig

__all__ = [
    "CartPoleEnv",
    "VectorEnv",
    "ENV_REGISTRY",
    "make_env",
    "SingleAgentEnvRunner",
    "EnvRunnerGroup",
    "JaxLearner",
    "PPO",
    "PPOConfig",
]
