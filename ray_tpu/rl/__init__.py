"""Reinforcement learning (reference: rllib/ new API stack —
EnvRunnerGroup + Learner + Algorithm), plus the ISSUE 13 decoupled
Sebulba-style dataflow (dataflow.py / rollout_queue.py /
weight_sync.py): `PPOConfig().dataflow()` / `DQNConfig().dataflow()`
switch either algorithm from the synchronous sample -> update ->
broadcast loop onto pipelined rollout/learner stages with
engine-served policy inference and drainless weight sync."""

from .actor_manager import CallResult, FaultTolerantActorManager
from .dataflow import (
    DataflowConfig,
    PolicyEngineActor,
    PolicyProgram,
    RLDataflow,
)
from .dqn import DQN, DecoupledDQN, DQNConfig, DQNLearner, ReplayBuffer
from .env import ENV_REGISTRY, CartPoleEnv, VectorEnv, make_env
from .env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from .learner import JaxLearner
from .learner_group import LearnerGroup
from .ppo import PPO, DecoupledPPO, PPOConfig
from .rollout_queue import RolloutQueue
from .weight_sync import WeightStore, push_weights

__all__ = [
    "CartPoleEnv",
    "VectorEnv",
    "ENV_REGISTRY",
    "make_env",
    "SingleAgentEnvRunner",
    "EnvRunnerGroup",
    "FaultTolerantActorManager",
    "CallResult",
    "JaxLearner",
    "LearnerGroup",
    "PPO",
    "PPOConfig",
    "DecoupledPPO",
    "DQN",
    "DQNConfig",
    "DQNLearner",
    "DecoupledDQN",
    "ReplayBuffer",
    "RLDataflow",
    "DataflowConfig",
    "PolicyProgram",
    "PolicyEngineActor",
    "RolloutQueue",
    "WeightStore",
    "push_weights",
]
