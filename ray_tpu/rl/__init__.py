"""Reinforcement learning (reference: rllib/ new API stack —
EnvRunnerGroup + Learner + Algorithm)."""

from .actor_manager import CallResult, FaultTolerantActorManager
from .dqn import DQN, DQNConfig, ReplayBuffer
from .env import ENV_REGISTRY, CartPoleEnv, VectorEnv, make_env
from .env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from .learner import JaxLearner
from .learner_group import LearnerGroup
from .ppo import PPO, PPOConfig

__all__ = [
    "CartPoleEnv",
    "VectorEnv",
    "ENV_REGISTRY",
    "make_env",
    "SingleAgentEnvRunner",
    "EnvRunnerGroup",
    "FaultTolerantActorManager",
    "CallResult",
    "JaxLearner",
    "LearnerGroup",
    "PPO",
    "PPOConfig",
    "DQN",
    "DQNConfig",
    "ReplayBuffer",
]
