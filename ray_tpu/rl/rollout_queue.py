"""Bounded, backpressured rollout-fragment queue (ISSUE 13).

The hand-off point of the decoupled RL dataflow (PAPERS: "Podracer
architectures" — Sebulba splits acting and learning into pipelined
stages; "MindSpeed RL" — a distributed-dataflow buffer between
rollout and train): env-runner actors PUSH fixed-shape rollout
fragments, the learner PULLS them, and neither side ever waits on the
other's compute — only on this queue's two explicit gates:

* **Capacity** (``rl_rollout_queue_capacity``): a full queue refuses
  puts (``"full"``) — the learner has fallen behind and runners must
  throttle instead of growing an unbounded staleness backlog.
* **Weight lag** (``rl_max_weight_lag``): each fragment carries the
  policy-weight version that generated it. A put more than
  ``max_weight_lag`` versions behind the learner's current version is
  refused (``"throttle"``: refresh weights, then retry), and a
  fragment that AGED past the bound while queued is dropped at get
  (counted, never trained on) — off-policy staleness is bounded by
  construction, not by hope.

Zero-copy discipline: fragments ride as *wrapped* object-store refs
(``{"ref": [ObjectRef]}``) — a ref nested in a container serializes
as a borrowed reference, so the payload bytes go runner → store →
learner without ever passing through this actor (the PR 9 arena makes
both hops zero-copy on one host). The queue holds only refs + a small
meta dict per fragment.

Every gate and level is a first-class metric (``rl_queue_*`` on
/metrics via the PR 7 pipe), which is what lets `ray_tpu doctor`
attribute an actor-vs-learner bottleneck: a queue pinned at capacity
convicts the learner; a queue pinned at zero with starving gets
convicts the runners.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = ["RolloutQueue", "QUEUE_METRIC_TAGS"]

QUEUE_METRIC_TAGS = ("queue",)


class RolloutQueue:
    """Actor body: deploy with ``rt.remote(num_cpus=0)(RolloutQueue)``
    (rl/dataflow.py does). Pure bookkeeping — never opens fragment
    payloads, never blocks a caller: both gates answer immediately
    and the CALLER decides how to wait (runners sleep-and-retry,
    the learner polls under its ``queue_wait_ms`` phase timer)."""

    def __init__(
        self,
        capacity: int = 16,
        max_weight_lag: int = 4,
        name: str = "rollout",
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_weight_lag < 0:
            raise ValueError(
                f"max_weight_lag must be >= 0, got {max_weight_lag}"
            )
        self.capacity = int(capacity)
        self.max_weight_lag = int(max_weight_lag)
        self._name = name
        self._frags: Deque[Dict[str, Any]] = deque()
        # Returned fragments are RETAINED for a while: this actor's
        # wrapped ref is what keeps the object-store payload alive
        # between "get_batch reply serialized" and "consumer
        # deserialized it" (the producer dropped its own ref right
        # after the put) — releasing at method return would race the
        # reply's borrow registration under load and free the block
        # mid-flight. A bounded ring of recent hand-offs closes the
        # window; consumers always resolve payloads promptly.
        self._returned: Deque[Dict[str, Any]] = deque(maxlen=64)
        self._learner_version = 0
        self._puts = 0
        self._gets = 0
        self._rejected_full = 0
        self._rejected_stale = 0
        self._dropped_stale = 0
        self._empty_gets = 0
        self._env_steps_in = 0
        # Occupancy integral for mean-depth reporting (rlbench's
        # queue-occupancy series): sum of depth x dwell-time.
        self._occ_t0 = time.monotonic()
        self._occ_area = 0.0
        self._tags = {"queue": name}

    # -- producer side -------------------------------------------------
    def put(self, item: Any, meta: Optional[dict] = None) -> str:
        """Offer one fragment. Returns ``"ok"`` (accepted),
        ``"full"`` (capacity backpressure: learner behind — wait and
        retry), or ``"throttle"`` (weight-lag gate: this fragment's
        policy version is already > max_weight_lag behind the
        learner — refresh weights before sampling more)."""
        meta = dict(meta or {})
        # Lag gate FIRST: a fragment too stale to ever be accepted
        # must throttle (drop + refresh) immediately — answering
        # "full" for it would have the runner spin-retrying data
        # that can only be rejected once space frees.
        version = int(meta.get("weight_version", self._learner_version))
        if self._learner_version - version > self.max_weight_lag:
            self._rejected_stale += 1
            self._observe("rl_queue_throttled_total")
            return "throttle"
        if len(self._frags) >= self.capacity:
            self._rejected_full += 1
            self._observe("rl_queue_full_total")
            return "full"
        self._tick_occupancy()
        self._frags.append({"item": item, "meta": meta})
        self._puts += 1
        self._env_steps_in += int(meta.get("env_steps", 0))
        self._observe("rl_queue_puts_total")
        self._gauges()
        return "ok"

    # -- consumer side -------------------------------------------------
    def get_batch(self, max_fragments: int = 8) -> List[Dict[str, Any]]:
        """Pop up to ``max_fragments`` fragments in FIFO order,
        dropping (and counting) any that aged past the weight-lag
        bound while queued. Returns immediately — an empty list means
        the runners have nothing ready (runner-bound signal)."""
        out: List[Dict[str, Any]] = []
        while self._frags and len(out) < int(max_fragments):
            self._tick_occupancy()
            frag = self._frags.popleft()
            version = int(
                frag["meta"].get(
                    "weight_version", self._learner_version
                )
            )
            if self._learner_version - version > self.max_weight_lag:
                self._dropped_stale += 1
                self._observe("rl_queue_stale_dropped_total")
                continue
            out.append(frag)
            self._returned.append(frag)
        if out:
            self._gets += len(out)
            self._observe("rl_queue_gets_total", float(len(out)))
        else:
            self._empty_gets += 1
            self._observe("rl_queue_empty_gets_total")
        self._gauges()
        return out

    def set_learner_version(self, version: int) -> int:
        """Advance the learner's published weight version — the
        reference point of both staleness gates. Monotonic."""
        self._learner_version = max(
            self._learner_version, int(version)
        )
        return self._learner_version

    # -- views ---------------------------------------------------------
    def depth(self) -> int:
        return len(self._frags)

    def ping(self) -> str:
        return "ok"

    def stats(self) -> Dict[str, Any]:
        elapsed = max(1e-9, time.monotonic() - self._occ_t0)
        area = self._occ_area + len(self._frags) * (
            time.monotonic()
            - getattr(self, "_occ_last", self._occ_t0)
        )
        return {
            "depth": len(self._frags),
            "capacity": self.capacity,
            "max_weight_lag": self.max_weight_lag,
            "learner_version": self._learner_version,
            "puts": self._puts,
            "gets": self._gets,
            "rejected_full": self._rejected_full,
            "rejected_stale": self._rejected_stale,
            "dropped_stale": self._dropped_stale,
            "empty_gets": self._empty_gets,
            "env_steps_in": self._env_steps_in,
            "mean_depth": round(area / elapsed, 3),
        }

    # -- internals -----------------------------------------------------
    def _tick_occupancy(self) -> None:
        now = time.monotonic()
        last = getattr(self, "_occ_last", self._occ_t0)
        self._occ_area += len(self._frags) * (now - last)
        self._occ_last = now

    def _observe(self, counter: str, value: float = 1.0) -> None:
        # Metrics must never fail queue traffic (same contract as the
        # engine's observe hooks); outside a session they're dropped
        # by the buffer, so unit tests need no cluster.
        try:
            from ..util.metrics import Counter

            metric = _METRICS.get(counter)
            if metric is None:
                metric = _METRICS[counter] = Counter(
                    counter,
                    description=_COUNTER_HELP.get(counter, counter),
                    tag_keys=QUEUE_METRIC_TAGS,
                )
            metric.inc(value, tags=self._tags)
        except Exception:
            pass

    def _gauges(self) -> None:
        try:
            from ..util.metrics import Gauge

            for name, value in (
                ("rl_queue_depth", float(len(self._frags))),
                ("rl_queue_capacity", float(self.capacity)),
                (
                    "rl_queue_learner_version",
                    float(self._learner_version),
                ),
            ):
                metric = _METRICS.get(name)
                if metric is None:
                    metric = _METRICS[name] = Gauge(
                        name,
                        description=_GAUGE_HELP.get(name, name),
                        tag_keys=QUEUE_METRIC_TAGS,
                    )
                metric.set(value, tags=self._tags)
        except Exception:
            pass


_METRICS: Dict[str, Any] = {}

_COUNTER_HELP = {
    "rl_queue_puts_total": "Rollout fragments accepted by the queue",
    "rl_queue_gets_total": "Rollout fragments handed to the learner",
    "rl_queue_full_total": (
        "Puts refused by capacity backpressure (learner behind)"
    ),
    "rl_queue_throttled_total": (
        "Puts refused by the weight-lag gate (runner weights stale)"
    ),
    "rl_queue_stale_dropped_total": (
        "Queued fragments dropped after aging past max_weight_lag"
    ),
    "rl_queue_empty_gets_total": (
        "Learner polls that found no fragment ready (runner-bound)"
    ),
}

_GAUGE_HELP = {
    "rl_queue_depth": "Rollout fragments currently queued",
    "rl_queue_capacity": "Rollout queue capacity bound",
    "rl_queue_learner_version": (
        "Learner weight version the staleness gates compare against"
    ),
}
