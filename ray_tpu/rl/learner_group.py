"""LearnerGroup: data-parallel learner sharding across actors.

Reference: rllib/core/learner/learner_group.py:81,206 — N learner
actors each take 1/N of the sample batch, compute gradients in
lockstep, and apply the ALL-REDUCED average so every learner's params
stay bit-identical (torch DDP in the reference). TPU-native split:

- On one HOST with a device mesh, multi-chip data parallelism needs no
  actors at all — the single JaxLearner's jitted update shards the
  minibatch over the mesh and XLA psums the gradients in-compile
  (learner.py docstring). That path stays the default.
- ACROSS hosts (or in tests standing in for hosts), this LearnerGroup
  runs one learner actor per shard with the reference's DDP protocol:
  per-minibatch gradient exchange through the object store, averaged
  once, applied everywhere. The effective minibatch size equals the
  single-learner configuration (each learner steps minibatch/N rows),
  so the 2-learner optimization trajectory matches the 1-learner one
  statistically — same effective batch, same step count.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

_BATCH_KEYS = (
    "obs",
    "actions",
    "logp",
    "advantages",
    "value_targets",
)


class _LearnerActor:
    """Actor body: one JaxLearner + its resident batch shard."""

    def __init__(self, learner_kwargs: dict, rank: int):
        from .learner import JaxLearner

        self.learner = JaxLearner(**learner_kwargs)
        self.rank = rank
        self._shard = None
        self._order = None

    def ping(self) -> str:
        return "ok"

    def set_batch(self, shard: Dict[str, np.ndarray]) -> int:
        import jax.numpy as jnp

        self._shard = {
            k: jnp.asarray(v) for k, v in shard.items()
        }
        return len(shard["obs"])

    def start_epoch(self, epoch: int) -> bool:
        """Shuffle this shard for the coming epoch. Seeded by
        (rank, epoch) so ranks draw independent permutations but runs
        are reproducible."""
        n = len(self._shard["obs"])
        rng = np.random.default_rng(
            (self.rank + 1) * 100_003 + epoch
        )
        self._order = rng.permutation(n)
        return True

    def grad_step(
        self, step: int, per_learner_mb: int
    ) -> Tuple[Dict, Dict]:
        idx = self._order[
            step * per_learner_mb : (step + 1) * per_learner_mb
        ]
        minibatch = {k: v[idx] for k, v in self._shard.items()}
        grads, metrics = self.learner.compute_gradients(minibatch)
        import jax

        return jax.device_get(grads), {
            k: float(v) for k, v in metrics.items()
        }

    def apply_grads(self, avg_grads) -> bool:
        self.learner.apply_gradients(avg_grads)
        return True

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, params) -> bool:
        self.learner.set_weights(params)
        return True


def _tree_mean(trees: List[Dict]):
    import jax

    return jax.tree_util.tree_map(
        lambda *leaves: sum(leaves) / len(leaves), *trees
    )


class LearnerGroup:
    """Drop-in for JaxLearner's update/get_weights/set_weights surface,
    fanning the update across `num_learners` actors."""

    def __init__(
        self,
        num_learners: int,
        *,
        minibatch_size: int = 256,
        num_epochs: int = 4,
        num_cpus_per_learner: float = 1.0,
        **learner_kwargs,
    ):
        import ray_tpu as rt

        assert num_learners >= 1
        if minibatch_size % num_learners:
            raise ValueError(
                f"minibatch_size {minibatch_size} must divide evenly "
                f"across {num_learners} learners"
            )
        self._rt = rt
        self.num_learners = num_learners
        self.minibatch_size = minibatch_size
        self.num_epochs = num_epochs
        learner_kwargs = dict(
            learner_kwargs,
            minibatch_size=minibatch_size,
            num_epochs=num_epochs,
        )
        actor_cls = rt.remote(num_cpus=num_cpus_per_learner)(
            _LearnerActor
        )
        self.learners = [
            actor_cls.remote(learner_kwargs, rank)
            for rank in range(num_learners)
        ]
        # Rank 0's init is canonical; everyone starts from it
        # (reference: LearnerGroup broadcasts a single init state).
        weights = rt.get(
            self.learners[0].get_weights.remote(), timeout=120
        )
        ref = rt.put(weights)
        rt.get(
            [
                learner.set_weights.remote(ref)
                for learner in self.learners[1:]
            ],
            timeout=120,
        )

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One PPO update pass, DDP-style (reference:
        learner_group.py:206 update_from_batch): split the batch into
        per-learner shards, then per minibatch step all learners
        gradient in lockstep and apply the same average."""
        rt = self._rt
        n = len(batch["obs"])
        world = self.num_learners
        shard_n = n // world
        shard_refs = []
        for rank in range(world):
            lo, hi = rank * shard_n, (rank + 1) * shard_n
            shard_refs.append(
                rt.put(
                    {
                        k: batch[k][lo:hi]
                        for k in _BATCH_KEYS
                        if k in batch
                    }
                )
            )
        rt.get(
            [
                learner.set_batch.remote(ref)
                for learner, ref in zip(self.learners, shard_refs)
            ],
            timeout=300,
        )
        # A batch smaller than one full minibatch must still train
        # (the single-learner path runs its start==0 ragged minibatch;
        # steps==0 here would silently skip the update forever).
        per_learner_mb = max(
            1, min(self.minibatch_size // world, shard_n)
        )
        steps = max(1, shard_n // per_learner_mb)
        metrics: Dict[str, float] = {}
        for epoch in range(self.num_epochs):
            rt.get(
                [
                    learner.start_epoch.remote(epoch)
                    for learner in self.learners
                ],
                timeout=300,
            )
            for step in range(steps):
                outs = rt.get(
                    [
                        learner.grad_step.remote(step, per_learner_mb)
                        for learner in self.learners
                    ],
                    timeout=300,
                )
                grads = _tree_mean([g for g, _ in outs])
                metric_dicts = [m for _, m in outs]
                metrics = {
                    k: float(
                        np.mean([m[k] for m in metric_dicts])
                    )
                    for k in metric_dicts[0]
                }
                grads_ref = rt.put(grads)
                rt.get(
                    [
                        learner.apply_grads.remote(grads_ref)
                        for learner in self.learners
                    ],
                    timeout=300,
                )
        return metrics

    def get_weights(self):
        return self._rt.get(
            self.learners[0].get_weights.remote(), timeout=120
        )

    def set_weights(self, params) -> None:
        ref = self._rt.put(params)
        self._rt.get(
            [
                learner.set_weights.remote(ref)
                for learner in self.learners
            ],
            timeout=120,
        )

    def shutdown(self) -> None:
        for learner in self.learners:
            try:
                self._rt.kill(learner)
            except Exception:
                pass
