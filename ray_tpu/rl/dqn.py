"""DQN algorithm.

Reference: rllib/algorithms/dqn/ — dqn.py (Algorithm.training_step:
sample -> replay buffer -> minibatch TD updates -> periodic target-net
sync) + dqn_rainbow_learner.py (Huber TD loss against a frozen target
network) + utils/replay_buffers/. TPU-native form: the Q-function is
the same pure-functional MLP the policy stack uses (models.py), the
TD update is one jitted step, and the replay buffer is preallocated
numpy rings (no per-transition Python objects).
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .env import VectorEnv, make_env


class ReplayBuffer:
    """Uniform-sampling ring buffer (reference:
    utils/replay_buffers/replay_buffer.py, storage_unit=timesteps)."""

    def __init__(self, capacity: int, obs_size: int, seed: int = 0):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_size), np.float32)
        self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.next_obs = np.zeros((capacity, obs_size), np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(
        self, obs, actions, rewards, next_obs, dones
    ) -> None:
        for i in range(len(actions)):
            j = self._next
            self.obs[j] = obs[i]
            self.actions[j] = actions[i]
            self.rewards[j] = rewards[i]
            self.next_obs[j] = next_obs[i]
            self.dones[j] = float(dones[i])
            self._next = (self._next + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "next_obs": self.next_obs[idx],
            "dones": self.dones[idx],
        }


class DQNLearner:
    """The gradient half of DQN — replay buffer + jitted double-DQN
    TD update + target sync — extracted (ISSUE 13) so the synchronous
    `DQN` loop and the decoupled dataflow train through ONE
    implementation. Satisfies the RLDataflow learner contract:
    `update(batch)` ingests a transition batch and takes the
    configured TD steps; `get_weights()/set_weights()` move the
    online net."""

    #: RLDataflow contract: batches land in the HOST-side replay
    #: ring (minibatches upload separately in _update_jit), so the
    #: driver's device-prefetch stage must pass them through as-is.
    host_ingest = True

    def __init__(
        self,
        obs_size: int,
        num_actions: int,
        *,
        lr: float = 5e-4,
        gamma: float = 0.99,
        hidden: Tuple[int, ...] = (64, 64),
        double_q: bool = True,
        buffer_capacity: int = 50_000,
        train_batch_size: int = 64,
        updates_per_batch: int = 128,
        target_update_freq: int = 100,
        learning_starts: int = 1_000,
        seed: int = 0,
    ):
        import jax
        import optax

        from .models import init_policy_params

        self.gamma = gamma
        self.double_q = double_q
        self.train_batch_size = train_batch_size
        self.updates_per_batch = updates_per_batch
        self.target_update_freq = target_update_freq
        self.learning_starts = learning_starts
        # The pi head doubles as the Q head (A outputs); vf unused.
        self.params = init_policy_params(
            jax.random.PRNGKey(seed), obs_size, num_actions, hidden
        )
        self.target_params = jax.device_get(self.params)
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.params)
        self.buffer = ReplayBuffer(
            buffer_capacity, obs_size, seed=seed
        )
        self.updates = 0
        from .._private import compile_watch

        self._update_jit = compile_watch.instrument(
            "rl.dqn.td_update", jax.jit(self._td_update)
        )
        self._q_jit = compile_watch.instrument(
            "rl.dqn.q_values", jax.jit(self._q_values)
        )

    # -- Q function ----------------------------------------------------
    @staticmethod
    def _q_values(params, obs):
        from .models import apply_policy

        q, _ = apply_policy(params, obs)
        return q

    def _td_update(self, params, target_params, opt_state, batch):
        import jax
        import jax.numpy as jnp
        import optax

        gamma = self.gamma

        def loss_fn(p):
            q = self._q_values(p, batch["obs"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1
            )[:, 0]
            q_next_target = self._q_values(
                target_params, batch["next_obs"]
            )
            if self.double_q:
                # Double-DQN: online net picks, target net evaluates
                # (reference: dqn_rainbow_learner.py double_q branch).
                q_next_online = self._q_values(p, batch["next_obs"])
                best = jnp.argmax(q_next_online, axis=1)
            else:
                best = jnp.argmax(q_next_target, axis=1)
            next_value = jnp.take_along_axis(
                q_next_target, best[:, None], axis=1
            )[:, 0]
            td_target = batch["rewards"] + gamma * next_value * (
                1.0 - batch["dones"]
            )
            td_target = jax.lax.stop_gradient(td_target)
            return jnp.mean(
                optax.huber_loss(q_taken, td_target)
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # -- training ------------------------------------------------------
    def ingest(self, batch: Dict[str, np.ndarray]) -> int:
        """Append one transition batch (obs/actions/rewards/next_obs/
        dones arrays) to the replay ring."""
        self.buffer.add_batch(
            np.asarray(batch["obs"]),
            np.asarray(batch["actions"]),
            np.asarray(batch["rewards"]),
            np.asarray(batch["next_obs"]),
            np.asarray(batch["dones"]),
        )
        return len(self.buffer)

    def td_steps(self, n: int) -> float:
        """`n` sampled TD minibatch updates + scheduled target syncs;
        returns the last loss (nan while below learning_starts)."""
        import jax

        loss = float("nan")
        if len(self.buffer) < self.learning_starts:
            return loss
        for _ in range(n):
            batch = self.buffer.sample(self.train_batch_size)
            device_batch = {
                k: np.asarray(v) for k, v in batch.items()
            }
            self.params, self.opt_state, loss = self._update_jit(
                self.params,
                self.target_params,
                self.opt_state,
                device_batch,
            )
            self.updates += 1
            if self.updates % self.target_update_freq == 0:
                self.target_params = jax.device_get(self.params)
        return float(loss)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """RLDataflow contract: one queue batch in, the configured TD
        steps out."""
        self.ingest(batch)
        loss = self.td_steps(self.updates_per_batch)
        return {"td_loss": loss, "num_updates": float(self.updates)}

    def get_weights(self):
        import jax

        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        import jax

        self.params = jax.device_put(params)


class DQNConfig:
    """Fluent builder (reference: DQNConfig(AlgorithmConfig))."""

    def __init__(self):
        self.env_spec: Any = "CartPole-v1"
        self.num_envs = 8
        self.rollout_length = 64  # vector steps per train() iteration
        self.gamma = 0.99
        self.lr = 5e-4
        self.buffer_capacity = 50_000
        self.train_batch_size = 64
        self.num_updates_per_iteration = 128
        self.learning_starts = 1_000  # transitions before updates
        # Updates between target syncs. Too-frequent syncing collapses
        # CartPole (measured: freq 8 plateaus at return ~10; freq 100
        # reaches 130+ by ~30k steps).
        self.target_update_freq = 100
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 8_000  # transitions to anneal over
        self.hidden = (64, 64)
        self.seed = 0
        self.double_q = True
        # Decoupled dataflow (ISSUE 13): off = the synchronous
        # act -> buffer -> update loop below.
        self.dataflow_enabled = False
        self.dataflow_policy = "local"
        self.num_env_runners = 2
        self.queue_capacity: Optional[int] = None
        self.max_weight_lag: Optional[int] = None
        self.sync_interval_updates: Optional[int] = None

    def environment(self, env) -> "DQNConfig":
        self.env_spec = env
        return self

    def env_runners(
        self,
        num_envs_per_env_runner: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
    ) -> "DQNConfig":
        if num_envs_per_env_runner is not None:
            self.num_envs = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_length = rollout_fragment_length
        return self

    def training(
        self,
        lr: Optional[float] = None,
        gamma: Optional[float] = None,
        train_batch_size: Optional[int] = None,
        target_network_update_freq: Optional[int] = None,
        num_steps_sampled_before_learning_starts: Optional[int] = None,
        double_q: Optional[bool] = None,
    ) -> "DQNConfig":
        for name, value in (
            ("lr", lr),
            ("gamma", gamma),
            ("train_batch_size", train_batch_size),
            ("target_update_freq", target_network_update_freq),
            ("learning_starts", num_steps_sampled_before_learning_starts),
            ("double_q", double_q),
        ):
            if value is not None:
                setattr(self, name, value)
        return self

    def debugging(self, seed: Optional[int] = None) -> "DQNConfig":
        if seed is not None:
            self.seed = seed
        return self

    def dataflow(
        self,
        enabled: bool = True,
        *,
        policy: Optional[str] = None,
        num_env_runners: Optional[int] = None,
        queue_capacity: Optional[int] = None,
        max_weight_lag: Optional[int] = None,
        sync_interval_updates: Optional[int] = None,
    ) -> "DQNConfig":
        """Switch `build()` to the decoupled dataflow: runner actors
        stream transition fragments through the rollout queue into
        this learner's replay buffer while TD updates run — DQN is
        replay-based, so staleness tolerance is native and
        `max_weight_lag` simply bounds how old the BEHAVIOR policy
        may be. Same knob semantics as PPOConfig.dataflow()."""
        self.dataflow_enabled = bool(enabled)
        if policy is not None:
            self.dataflow_policy = policy
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if queue_capacity is not None:
            self.queue_capacity = queue_capacity
        if max_weight_lag is not None:
            self.max_weight_lag = max_weight_lag
        if sync_interval_updates is not None:
            self.sync_interval_updates = sync_interval_updates
        return self

    def build(self):
        if self.dataflow_enabled:
            return DecoupledDQN(self)
        return DQN(self)


class DQN:
    """(reference: dqn.py DQN(Algorithm) — train()/save/restore)."""

    def __init__(self, config: DQNConfig):
        self.config = config
        probe = make_env(config.env_spec, seed=0)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        # REWIRED (ISSUE 13): the gradient half lives in DQNLearner —
        # the same object the decoupled dataflow trains through.
        self.learner = DQNLearner(
            self.obs_size,
            self.num_actions,
            lr=config.lr,
            gamma=config.gamma,
            hidden=config.hidden,
            double_q=config.double_q,
            buffer_capacity=config.buffer_capacity,
            train_batch_size=config.train_batch_size,
            updates_per_batch=config.num_updates_per_iteration,
            target_update_freq=config.target_update_freq,
            learning_starts=config.learning_starts,
            seed=config.seed,
        )
        self.vec = VectorEnv(
            lambda s: make_env(config.env_spec, seed=s),
            config.num_envs,
            seed=config.seed,
        )
        self._obs = self.vec.reset()
        self._rng = np.random.default_rng(config.seed)
        self.iteration = 0
        self.env_steps = 0
        self._ep_returns = np.zeros(config.num_envs)
        self._recent_returns: list = []

    # -- learner views (kept for compatibility) -----------------------
    @property
    def params(self):
        return self.learner.params

    @property
    def target_params(self):
        return self.learner.target_params

    @property
    def buffer(self) -> ReplayBuffer:
        return self.learner.buffer

    @property
    def updates(self) -> int:
        return self.learner.updates

    # -- acting --------------------------------------------------------
    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.env_steps / cfg.epsilon_decay_steps)
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial
        )

    def _act(self, obs: np.ndarray) -> np.ndarray:
        eps = self._epsilon()
        greedy = np.asarray(
            np.argmax(self.learner._q_jit(self.params, obs), axis=1)
        )
        explore = self._rng.integers(
            0, self.num_actions, size=len(obs)
        )
        coin = self._rng.random(len(obs)) < eps
        return np.where(coin, explore, greedy).astype(np.int32)

    # -- one iteration (reference: DQN.training_step) -----------------
    def train(self) -> Dict[str, Any]:
        cfg = self.config
        for _ in range(cfg.rollout_length):
            actions = self._act(self._obs)
            next_obs, rewards, terminated, truncated = self.vec.step(
                actions
            )
            self.buffer.add_batch(
                self._obs, actions, rewards, next_obs, terminated
            )
            self.env_steps += len(actions)
            self._ep_returns += rewards
            for i in range(len(actions)):
                if terminated[i] or truncated[i]:
                    self._recent_returns.append(
                        float(self._ep_returns[i])
                    )
                    self._ep_returns[i] = 0.0
            self._obs = next_obs
        loss = self.learner.td_steps(cfg.num_updates_per_iteration)
        self.iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (
            float(np.mean(self._recent_returns))
            if self._recent_returns
            else float("nan")
        )
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "num_env_steps_sampled": self.env_steps,
            "num_updates": self.updates,
            "epsilon": self._epsilon(),
            "td_loss": loss,
        }

    # -- checkpointing (reference: Algorithm.save/restore) ------------
    def save(self, path: Optional[str] = None) -> str:
        import jax

        path = path or tempfile.mkdtemp(prefix="rt_dqn_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "state.pkl"), "wb") as f:
            pickle.dump(
                {
                    "params": jax.device_get(self.params),
                    "target_params": self.target_params,
                    "iteration": self.iteration,
                    "env_steps": self.env_steps,
                    "updates": self.updates,
                },
                f,
            )
        return path

    def restore(self, path: str) -> None:
        import jax

        with open(os.path.join(path, "state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner.params = jax.device_put(state["params"])
        self.learner.target_params = state["target_params"]
        self.iteration = state["iteration"]
        self.env_steps = state["env_steps"]
        self.learner.updates = state["updates"]

    def stop(self) -> None:
        pass


class DecoupledDQN:
    """DQN rewired onto the decoupled dataflow (ISSUE 13): runner
    actors explore with engine-served (or runner-local) Q inference
    and stream transition fragments through the rollout queue into
    the shared DQNLearner's replay buffer; TD updates and target
    syncs run while the fleet keeps sampling. Epsilon anneals on the
    DRIVER's global env-step count and ships with each runner call,
    so exploration scheduling matches the synchronous loop."""

    def __init__(self, config: DQNConfig):
        from .dataflow import DataflowConfig, RLDataflow

        self.config = config
        probe = make_env(config.env_spec, seed=0)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.learner = DQNLearner(
            self.obs_size,
            self.num_actions,
            lr=config.lr,
            gamma=config.gamma,
            hidden=config.hidden,
            double_q=config.double_q,
            buffer_capacity=config.buffer_capacity,
            train_batch_size=config.train_batch_size,
            updates_per_batch=config.num_updates_per_iteration,
            target_update_freq=config.target_update_freq,
            learning_starts=config.learning_starts,
            seed=config.seed,
        )

        def epsilon(env_steps: int) -> float:
            frac = min(
                1.0, env_steps / config.epsilon_decay_steps
            )
            return config.epsilon_initial + frac * (
                config.epsilon_final - config.epsilon_initial
            )

        self._epsilon_fn = epsilon
        self.flow = RLDataflow(
            self.learner,
            env_spec=config.env_spec,
            obs_size=self.obs_size,
            num_env_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs,
            rollout_length=config.rollout_length,
            gamma=config.gamma,
            gae_lambda=0.0,  # unused by the dqn fragment path
            seed=config.seed,
            algo="dqn",
            flow=DataflowConfig(
                policy=config.dataflow_policy,
                queue_capacity=config.queue_capacity,
                max_weight_lag=config.max_weight_lag,
                sync_interval_updates=config.sync_interval_updates,
            ),
            epsilon_fn=epsilon,
        )
        self.iteration = 0

    @property
    def env_steps(self) -> int:
        return self.flow._env_steps

    @property
    def updates(self) -> int:
        return self.learner.updates

    def train(self) -> Dict[str, Any]:
        metrics = self.flow.train_update()
        self.iteration += 1
        stats = self.flow.stats()
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": stats["episode_return_mean"],
            "num_env_steps_sampled": stats["env_steps"],
            "num_updates": self.learner.updates,
            "epsilon": self._epsilon_fn(stats["env_steps"]),
            "td_loss": metrics.get("td_loss", float("nan")),
            "weight_version": metrics.get("weight_version", 0),
        }

    def stop(self) -> None:
        self.flow.shutdown()
