"""PPO algorithm.

Reference: rllib/algorithms/ppo/ppo.py:374,400 — Algorithm.train()
runs training_step(): EnvRunnerGroup sample fan-out -> learner update
-> weights broadcast back to runners; config via the fluent
AlgorithmConfig builder (algorithm_config.py).
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict, Optional

import numpy as np

from .env import make_env
from .env_runner import EnvRunnerGroup
from .learner import JaxLearner


class PPOConfig:
    """Fluent builder (reference: AlgorithmConfig)."""

    def __init__(self):
        self.env_spec: Any = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_runner = 16
        self.rollout_length = 128
        self.gamma = 0.99
        self.gae_lambda = 0.95
        self.lr = 1e-3
        self.clip_eps = 0.2
        self.vf_coef = 0.5
        self.entropy_coef = 0.01
        self.minibatch_size = 128
        self.num_epochs = 4
        self.hidden = (64, 64)
        self.seed = 0
        self.num_learners = 1
        # Decoupled dataflow (ISSUE 13): off = the synchronous
        # sample -> update -> broadcast loop below (kept as the
        # rlbench baseline).
        self.dataflow_enabled = False
        self.dataflow_policy = "local"
        self.queue_capacity: Optional[int] = None
        self.max_weight_lag: Optional[int] = None
        self.sync_interval_updates: Optional[int] = None
        self.updates_per_iteration = 1

    def environment(self, env) -> "PPOConfig":
        self.env_spec = env
        return self

    def env_runners(
        self,
        num_env_runners: Optional[int] = None,
        num_envs_per_env_runner: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
    ) -> "PPOConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_length = rollout_fragment_length
        return self

    def training(
        self,
        lr: Optional[float] = None,
        gamma: Optional[float] = None,
        clip_param: Optional[float] = None,
        entropy_coeff: Optional[float] = None,
        vf_loss_coeff: Optional[float] = None,
        minibatch_size: Optional[int] = None,
        num_epochs: Optional[int] = None,
    ) -> "PPOConfig":
        for name, value in (
            ("lr", lr),
            ("gamma", gamma),
            ("clip_eps", clip_param),
            ("entropy_coef", entropy_coeff),
            ("vf_coef", vf_loss_coeff),
            ("minibatch_size", minibatch_size),
            ("num_epochs", num_epochs),
        ):
            if value is not None:
                setattr(self, name, value)
        return self

    def learners(
        self, num_learners: Optional[int] = None
    ) -> "PPOConfig":
        """Data-parallel learner count (reference:
        AlgorithmConfig.learners(num_learners=...)). 1 = in-process
        JaxLearner (whole-mesh GSPMD); >1 = LearnerGroup actors with
        per-minibatch gradient all-reduce."""
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def debugging(self, seed: Optional[int] = None) -> "PPOConfig":
        if seed is not None:
            self.seed = seed
        return self

    def dataflow(
        self,
        enabled: bool = True,
        *,
        policy: Optional[str] = None,
        queue_capacity: Optional[int] = None,
        max_weight_lag: Optional[int] = None,
        sync_interval_updates: Optional[int] = None,
        updates_per_iteration: Optional[int] = None,
    ) -> "PPOConfig":
        """Switch `build()` to the decoupled Sebulba-style dataflow
        (rl/dataflow.py): runner actors stream fragments through the
        bounded rollout queue while the learner trains, with
        drainless versioned weight sync. ``policy="engine"`` serves
        rollout inference from a continuous-batching policy engine
        (the RLHF shape); ``"local"`` keeps inference in the runners
        (classic Sebulba, the apples-to-apples rlbench comparison).
        Unset knobs fall back to the ``rl_*`` runtime config keys."""
        self.dataflow_enabled = bool(enabled)
        if policy is not None:
            self.dataflow_policy = policy
        if queue_capacity is not None:
            self.queue_capacity = queue_capacity
        if max_weight_lag is not None:
            self.max_weight_lag = max_weight_lag
        if sync_interval_updates is not None:
            self.sync_interval_updates = sync_interval_updates
        if updates_per_iteration is not None:
            self.updates_per_iteration = updates_per_iteration
        return self

    def build(self):
        if self.dataflow_enabled:
            return DecoupledPPO(self)
        return PPO(self)


class PPO:
    """(reference: Algorithm(Trainable) — train()/save/restore)."""

    def __init__(self, config: PPOConfig):
        self.config = config
        probe = make_env(config.env_spec, seed=0)
        learner_kwargs = dict(
            obs_size=probe.observation_size,
            num_actions=probe.num_actions,
            lr=config.lr,
            clip_eps=config.clip_eps,
            vf_coef=config.vf_coef,
            entropy_coef=config.entropy_coef,
            minibatch_size=config.minibatch_size,
            num_epochs=config.num_epochs,
            hidden=config.hidden,
            seed=config.seed,
        )
        if config.num_learners > 1:
            from .learner_group import LearnerGroup

            self.learner = LearnerGroup(
                config.num_learners, **learner_kwargs
            )
        else:
            self.learner = JaxLearner(**learner_kwargs)
        self.env_runners = EnvRunnerGroup(
            config.env_spec,
            num_env_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            rollout_length=config.rollout_length,
            gamma=config.gamma,
            gae_lambda=config.gae_lambda,
            seed=config.seed,
        )
        self.env_runners.sync_weights(self.learner.get_weights())
        self.iteration = 0
        self._recent_returns: list = []

    def train(self) -> Dict[str, Any]:
        """One iteration (reference: PPO.training_step, ppo.py:400)."""
        batch = self.env_runners.sample()
        episode_returns = batch.pop("episode_returns")
        metrics = self.learner.update(batch)
        self.env_runners.sync_weights(self.learner.get_weights())
        self.iteration += 1
        self._recent_returns.extend(episode_returns.tolist())
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (
            float(np.mean(self._recent_returns))
            if self._recent_returns
            else float("nan")
        )
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "num_env_steps_sampled": len(batch["obs"]),
            **metrics,
        }

    # -- checkpointing (reference: Algorithm.save/restore) ------------
    def save(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="rt_ppo_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "weights.pkl"), "wb") as f:
            pickle.dump(
                {
                    "params": self.learner.get_weights(),
                    "iteration": self.iteration,
                },
                f,
            )
        return path

    def restore(self, path: str) -> None:
        with open(os.path.join(path, "weights.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner.set_weights(state["params"])
        self.iteration = state["iteration"]
        self.env_runners.sync_weights(self.learner.get_weights())

    def stop(self) -> None:
        self.env_runners.shutdown()
        shutdown = getattr(self.learner, "shutdown", None)
        if shutdown is not None:
            shutdown()


class DecoupledPPO:
    """PPO rewired onto the decoupled dataflow (ISSUE 13): same
    config surface, same `train()` result keys as `PPO`, but rollout
    collection, policy inference and learning run as pipelined stages
    over the rollout queue instead of alternating behind a gather
    barrier. One `train()` = `updates_per_iteration` learner updates,
    each consuming the same row count the synchronous path samples
    per iteration — updates-per-env-step parity is what keeps the
    rlbench comparison honest."""

    def __init__(self, config: PPOConfig):
        from .dataflow import DataflowConfig, RLDataflow

        self.config = config
        probe = make_env(config.env_spec, seed=0)
        self.learner = JaxLearner(
            obs_size=probe.observation_size,
            num_actions=probe.num_actions,
            lr=config.lr,
            clip_eps=config.clip_eps,
            vf_coef=config.vf_coef,
            entropy_coef=config.entropy_coef,
            minibatch_size=config.minibatch_size,
            num_epochs=config.num_epochs,
            hidden=config.hidden,
            seed=config.seed,
        )
        self.flow = RLDataflow(
            self.learner,
            env_spec=config.env_spec,
            obs_size=probe.observation_size,
            num_env_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            rollout_length=config.rollout_length,
            gamma=config.gamma,
            gae_lambda=config.gae_lambda,
            seed=config.seed,
            algo="ppo",
            flow=DataflowConfig(
                policy=config.dataflow_policy,
                queue_capacity=config.queue_capacity,
                max_weight_lag=config.max_weight_lag,
                sync_interval_updates=config.sync_interval_updates,
            ),
        )
        self.iteration = 0

    def train(self) -> Dict[str, Any]:
        rows = (
            self.config.num_env_runners
            * self.config.num_envs_per_runner
            * self.config.rollout_length
        )
        metrics: Dict[str, Any] = {}
        for _ in range(max(1, self.config.updates_per_iteration)):
            metrics = self.flow.train_update()
        self.iteration += 1
        stats = self.flow.stats()
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": stats["episode_return_mean"],
            "num_env_steps_sampled": rows
            * max(1, self.config.updates_per_iteration),
            "env_steps_total": stats["env_steps"],
            **metrics,
        }

    # -- checkpointing (same format as PPO.save/restore) --------------
    def save(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="rt_ppo_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "weights.pkl"), "wb") as f:
            pickle.dump(
                {
                    "params": self.learner.get_weights(),
                    "iteration": self.iteration,
                },
                f,
            )
        return path

    def restore(self, path: str) -> None:
        from .weight_sync import push_weights

        with open(os.path.join(path, "weights.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner.set_weights(state["params"])
        self.iteration = state["iteration"]
        # Restored weights must reach the serving side like any
        # learner update: a drainless versioned push.
        self.flow._version += 1
        push_weights(
            self.learner.get_weights(),
            self.flow._version,
            engines=(
                [self.flow._engine]
                if self.flow._engine is not None else []
            ),
            store=self.flow._store,
            queue=self.flow._queue,
        )

    def stop(self) -> None:
        self.flow.shutdown()
