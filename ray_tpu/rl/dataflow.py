"""Decoupled RL dataflow: Sebulba-style rollout/learner split
(ISSUE 13 tentpole).

The synchronous path (`PPO.train`: sample -> update -> broadcast) is
a gather barrier — actors idle while the learner trains and the
learner idles while actors sample. This module splits the loop into
pipelined stages that only meet at explicit, instrumented seams
(PAPERS: "Podracer architectures for scalable Reinforcement
Learning"; "MindSpeed RL: Distributed Dataflow for Scalable and
Efficient RL Training"):

  env-runner actors --(fixed-shape fragments, zero-copy refs)-->
      RolloutQueue (bounded + weight-lag gated, rollout_queue.py)
          --(prefetch pipeline, queue-wait billed like data_wait)-->
              learner (in-driver jitted update)
                  --(drainless versioned push, weight_sync.py)-->
                      engine / weight store / queue version gates

Policy inference during rollout runs in one of two modes:

* ``policy="local"`` — classic Sebulba: each runner holds the policy
  params and samples on-CPU, refreshing from the WeightStore between
  fragments. Identical per-step work to the synchronous baseline, so
  rlbench's comparison isolates pure dataflow overlap.
* ``policy="engine"`` — the RLHF shape: runners hold NO weights and
  call a continuous-batching `InferenceEngine` (llm/engine.py policy
  path) whose step loop coalesces all runners' ragged per-env
  requests into one batched forward; weight pushes land in the
  engine WITHOUT draining it.

The driver is single-threaded and keeps every runner saturated with a
2-deep call pipeline (a runner finishes fragment N and immediately
starts N+1 from its mailbox; the driver only tops the mailbox up), so
rollout and learning overlap without background threads in the
driver. A dead runner costs its in-flight fragment, never the flow:
the driver respawns the slot, re-syncs weights, and keeps pumping.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "PolicyProgram",
    "PolicyEngineActor",
    "RLDataflow",
    "DataflowConfig",
]


# ---------------------------------------------------------------------
# policy batch program (the engine's pluggable non-LLM path)
# ---------------------------------------------------------------------

class PolicyProgram:
    """BatchProgram serving the rl/models.py MLP policy: one jitted
    forward over a padded observation batch -> sampled actions,
    greedy actions (DQN's argmax head), log-probs and values. Padded
    rows are junk-in/junk-out — the engine slices each ticket's rows
    back out, so padding never leaks (same contract as the LLM
    path's masked dead slots)."""

    def __init__(
        self,
        obs_size: int,
        buckets: Tuple[int, ...] = (8, 16, 32, 64, 128, 256),
    ):
        import jax

        self.obs_size = int(obs_size)
        self.buckets = tuple(sorted(int(b) for b in buckets))

        def _run(params, obs, key):
            import jax.numpy as jnp

            from .models import apply_policy

            logits, values = apply_policy(params, obs)
            actions = jax.random.categorical(key, logits)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), actions[:, None], axis=1
            )[:, 0]
            greedy = jnp.argmax(logits, axis=1)
            return {
                "actions": actions,
                "greedy": greedy,
                "logp": logp,
                "values": values,
            }

        from .._private import compile_watch

        self._jit = compile_watch.instrument(
            "rl.policy_program", jax.jit(_run)
        )

    def bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]

    def run(self, params, inputs, key) -> Dict[str, Any]:
        return self._jit(params, inputs, key)


class PolicyEngineActor:
    """Actor body hosting a policy-only InferenceEngine. Deploy with
    ``max_concurrency > num_runners`` so concurrent `act` calls park
    on tickets while the engine's step loop batches them — the
    continuous-batching win over per-runner inference. Engine death
    surfaces as `EngineDead` to every pending caller, fast."""

    def __init__(
        self,
        params: Dict[str, Any],
        obs_size: int,
        *,
        buckets: Tuple[int, ...] = (8, 16, 32, 64, 128, 256),
        seed: int = 0,
    ):
        from ..llm.engine import EngineConfig, InferenceEngine

        self._engine = InferenceEngine(
            params,
            None,  # policy-only: no KV cache, no slot machinery
            EngineConfig(seed=seed),
            family="rl-policy",
            program=PolicyProgram(obs_size, buckets),
        )

    def act(self, obs) -> Dict[str, Any]:
        ticket = self._engine.submit_policy(np.asarray(obs))
        out = dict(ticket.result(timeout=60.0))
        out["weight_version"] = ticket.version
        return out

    def update_weights(self, params, *, version: int) -> int:
        return self._engine.update_weights(params, version=version)

    def stats(self) -> Dict[str, Any]:
        return self._engine.stats()

    def die(self) -> None:
        """Chaos hook: kill the ENGINE LOOP (not the actor) so tests
        can prove pending policy requests fail fast with EngineDead
        instead of hanging."""
        self._engine.close()

    def ping(self) -> str:
        return "ok"


# ---------------------------------------------------------------------
# env-runner actor
# ---------------------------------------------------------------------

class _DataflowRunner:
    """Actor body: vectorized envs + one fragment per call.

    The driver paces calls (2-deep pipeline); each call samples one
    fixed-shape fragment, `rt.put`s it (zero-copy arena block) and
    offers the WRAPPED ref to the rollout queue, honoring both
    backpressure gates. Episode state (env positions, running
    returns) lives here, so a dropped fragment never corrupts
    episode accounting."""

    def __init__(
        self,
        env_spec,
        num_envs: int,
        rollout_length: int,
        gamma: float,
        gae_lambda: float,
        seed: int,
        runner_id: int,
        queue,
        *,
        engine=None,
        weight_store=None,
        algo: str = "ppo",
    ):
        import jax

        from .env import VectorEnv, make_env

        self.vec = VectorEnv(
            lambda s: make_env(env_spec, seed=s), num_envs, seed=seed
        )
        self.rollout_length = int(rollout_length)
        self.gamma = gamma
        self.lam = gae_lambda
        self.runner_id = int(runner_id)
        self.algo = algo
        self._queue = queue
        self._engine = engine
        self._store = weight_store
        self._params = None
        self._version = 0
        self._key = jax.random.PRNGKey(seed)
        self._obs = self.vec.reset()
        self._ep_returns = np.zeros(num_envs)
        self._finished: List[float] = []
        self._rng = np.random.default_rng(seed ^ 0xC0FFEE)
        # Local inference runs the SAME batch program the engine path
        # serves — one compile per runner (fixed [num_envs, obs]
        # shape), identical outputs, so the two modes differ only in
        # WHERE the forward runs.
        self._program = PolicyProgram(self._obs.shape[1])

    def ping(self) -> str:
        return "ok"

    def set_weights(self, params, version: int = 0) -> int:
        self._params = params
        self._version = int(version)
        return self._version

    # -- policy inference ---------------------------------------------
    def _refresh_weights(self) -> None:
        """Local mode: pull newer weights from the store if the
        version moved (one int RPC in the common no-op case)."""
        if self._store is None:
            return
        import ray_tpu as rt

        latest = rt.get(
            self._store.latest_version.remote(), timeout=30
        )
        if latest > self._version:
            version, item = rt.get(
                self._store.get.remote(), timeout=30
            )
            if item is not None:
                self._params = rt.get(item[0], timeout=30)
                self._version = int(version)

    def _act(self, obs: np.ndarray, epsilon: float) -> Dict[str, Any]:
        if self._engine is not None:
            import ray_tpu as rt

            out = rt.get(self._engine.act.remote(obs), timeout=60)
            self._version = int(out.get("weight_version") or 0)
        else:
            import jax

            assert self._params is not None, "set_weights first"
            self._key, sub = jax.random.split(self._key)
            out = {
                k: np.asarray(v)
                for k, v in self._program.run(
                    self._params, obs, sub
                ).items()
            }
        if self.algo == "dqn":
            # Epsilon-greedy over the greedy (argmax-Q) head,
            # explored runner-side so the batch program stays
            # stateless and shared across algorithms.
            n = len(obs)
            explore = self._rng.integers(
                0, self._num_actions(), size=n
            )
            coin = self._rng.random(n) < epsilon
            out = dict(out)
            out["actions"] = np.where(
                coin, explore, np.asarray(out["greedy"])
            ).astype(np.int64)
        return out

    def _num_actions(self) -> int:
        return self.vec.envs[0].num_actions

    # -- one fragment --------------------------------------------------
    def sample_and_put(
        self,
        *,
        epsilon: float = 0.0,
        put_retry_s: float = 0.02,
        put_deadline_s: float = 30.0,
    ) -> Dict[str, Any]:
        import ray_tpu as rt

        self._refresh_weights()
        t0 = time.perf_counter()
        T, N = self.rollout_length, self.vec.num_envs
        obs_buf = np.zeros((T, N, self._obs.shape[1]), np.float32)
        act_buf = np.zeros((T, N), np.int32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        next_obs_buf = (
            np.zeros((T, N, self._obs.shape[1]), np.float32)
            if self.algo == "dqn" else None
        )
        act_ms = 0.0
        version_floor: Optional[int] = None
        for t in range(T):
            a0 = time.perf_counter()
            out = self._act(self._obs, epsilon)
            act_ms += (time.perf_counter() - a0) * 1e3
            if version_floor is None:
                version_floor = self._version
            version_floor = min(version_floor, self._version)
            actions = np.asarray(out["actions"])
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = np.asarray(out["logp"])
            val_buf[t] = np.asarray(out["values"])
            next_obs, rewards, terminated, truncated = self.vec.step(
                actions
            )
            rew_buf[t] = rewards
            done_buf[t] = terminated
            if next_obs_buf is not None:
                next_obs_buf[t] = next_obs
            self._ep_returns += rewards
            for i in range(N):
                if terminated[i] or truncated[i]:
                    self._finished.append(float(self._ep_returns[i]))
                    self._ep_returns[i] = 0.0
            self._obs = next_obs
        if self.algo == "ppo":
            from .env_runner import compute_gae

            last_out = self._act(self._obs, 0.0)
            last_values = np.asarray(last_out["values"])
            adv = compute_gae(
                rew_buf, val_buf, done_buf, last_values,
                self.gamma, self.lam,
            )
            returns = adv + val_buf
            flat = lambda a: a.reshape(-1, *a.shape[2:])  # noqa: E731
            fragment = {
                "obs": flat(obs_buf),
                "actions": flat(act_buf),
                "logp": flat(logp_buf),
                "advantages": flat(adv),
                "value_targets": flat(returns),
            }
        else:
            flat = lambda a: a.reshape(-1, *a.shape[2:])  # noqa: E731
            fragment = {
                "obs": flat(obs_buf),
                "actions": flat(act_buf),
                "rewards": flat(rew_buf),
                "next_obs": flat(next_obs_buf),
                "dones": flat(done_buf).astype(np.float32),
            }
        meta = {
            "runner": self.runner_id,
            "weight_version": int(version_floor or 0),
            "env_steps": T * N,
            "ts": time.time(),
        }
        episode_returns = self._finished
        self._finished = []
        # Offer under both gates: "full" waits (learner behind —
        # capacity backpressure), "throttle" refreshes weights and
        # re-offers under the new version IF the fragment is still
        # inside the lag bound — otherwise it is dropped (stale data
        # must not train).
        ref = rt.put(fragment)
        status = "dropped"
        waits_full = 0
        throttles = 0
        deadline = time.monotonic() + put_deadline_s
        while time.monotonic() < deadline:
            verdict = rt.get(
                self._queue.put.remote({"ref": [ref]}, meta),
                timeout=30,
            )
            if verdict == "ok":
                status = "ok"
                break
            if verdict == "full":
                waits_full += 1
                time.sleep(put_retry_s)
                continue
            # "throttle": this fragment's policy version aged past
            # max_weight_lag while sampling — it must not train.
            # Refresh so the NEXT fragment is fresh, drop this one.
            throttles += 1
            self._refresh_weights()
            status = "dropped_stale"
            break
        return {
            "runner": self.runner_id,
            "status": status,
            "env_steps": T * N,
            "weight_version": int(version_floor or 0),
            "episode_returns": episode_returns,
            "act_ms": round(act_ms, 3),
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "waits_full": waits_full,
            "throttles": throttles,
        }


# ---------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------

class DataflowConfig:
    """Knobs of the decoupled dataflow; defaults pull from the
    runtime config (``rl_rollout_queue_capacity``,
    ``rl_max_weight_lag``, ``rl_weight_sync_interval_updates`` —
    documented in _private/config.py, overridable per-run here)."""

    def __init__(
        self,
        *,
        policy: str = "local",
        queue_capacity: Optional[int] = None,
        max_weight_lag: Optional[int] = None,
        sync_interval_updates: Optional[int] = None,
        runner_pipeline_depth: int = 0,
        update_rows: Optional[int] = None,
        engine_buckets: Tuple[int, ...] = (8, 16, 32, 64, 128, 256),
    ):
        from .._private.config import Config

        if policy not in ("local", "engine"):
            raise ValueError(
                f"policy must be 'local' or 'engine', got {policy!r}"
            )
        runtime = Config.from_env()
        self.policy = policy
        self.queue_capacity = int(
            queue_capacity
            if queue_capacity is not None
            else runtime.rl_rollout_queue_capacity
        )
        self.max_weight_lag = int(
            max_weight_lag
            if max_weight_lag is not None
            else runtime.rl_max_weight_lag
        )
        self.sync_interval_updates = int(
            sync_interval_updates
            if sync_interval_updates is not None
            else runtime.rl_weight_sync_interval_updates
        )
        #: Queued sample calls per runner MAILBOX. The driver is
        #: single-threaded: while the learner's update runs, runners
        #: drain their mailboxes back-to-back — the depth must cover
        #: one update's wall or the fleet idles mid-update. 0 = auto:
        #: spread the queue capacity across the fleet (the queue's
        #: own gates remain the real backpressure bound).
        self.runner_pipeline_depth = int(runner_pipeline_depth)
        self.update_rows = update_rows
        self.engine_buckets = tuple(engine_buckets)

    def resolved_pipeline_depth(self, num_runners: int) -> int:
        if self.runner_pipeline_depth > 0:
            return self.runner_pipeline_depth
        per_runner = (
            self.queue_capacity + num_runners - 1
        ) // max(1, num_runners)
        return max(2, min(16, per_runner))


class RLDataflow:
    """The composed dataflow driver: owns the queue, the weight path,
    the runner fleet (and, in engine mode, the policy engine actor),
    and drives the learner against the queue through a device
    prefetch pipeline. `learner` is any object with
    ``update(batch) -> metrics`` / ``get_weights()`` (JaxLearner, a
    LearnerGroup, or the DQNLearner adapter)."""

    def __init__(
        self,
        learner,
        *,
        env_spec,
        obs_size: int,
        num_env_runners: int = 2,
        num_envs_per_runner: int = 8,
        rollout_length: int = 64,
        gamma: float = 0.99,
        gae_lambda: float = 0.95,
        seed: int = 0,
        algo: str = "ppo",
        flow: Optional[DataflowConfig] = None,
        epsilon_fn: Optional[Callable[[int], float]] = None,
    ):
        import ray_tpu as rt

        self._rt = rt
        self.learner = learner
        self.flow = flow or DataflowConfig()
        self.algo = algo
        self._epsilon_fn = epsilon_fn or (lambda env_steps: 0.0)
        self._env_spec = env_spec
        self._seed = seed
        self._version = 0
        self._updates = 0
        self._env_steps = 0
        self._fragments_ok = 0
        self._fragments_dropped = 0
        self._frags_by_runner: Dict[int, int] = {}
        self._runner_failures = 0
        self._waits_full = 0
        self._throttles = 0
        self._last_sync_ms = 0.0
        self._recent_returns: List[float] = []
        self._stopped = False
        cfg = self.flow
        self._update_rows = cfg.update_rows or (
            num_env_runners * num_envs_per_runner * rollout_length
        )

        from .rollout_queue import RolloutQueue
        from .weight_sync import WeightStore

        queue_cls = rt.remote(num_cpus=0)(RolloutQueue)
        self._queue = queue_cls.remote(
            cfg.queue_capacity, cfg.max_weight_lag
        )
        self._store = None
        self._engine = None
        params0 = learner.get_weights()
        if cfg.policy == "engine":
            engine_cls = rt.remote(
                num_cpus=0,
                max_concurrency=max(4, num_env_runners + 2),
            )(PolicyEngineActor)
            self._engine = engine_cls.remote(
                params0,
                obs_size,
                buckets=cfg.engine_buckets,
                seed=seed,
            )
            rt.get(self._engine.ping.remote(), timeout=60)
        else:
            store_cls = rt.remote(num_cpus=0)(WeightStore)
            self._store = store_cls.remote()

        runner_cls = rt.remote(num_cpus=1)(_DataflowRunner)

        def make_runner(idx: int):
            return runner_cls.remote(
                env_spec,
                num_envs_per_runner,
                rollout_length,
                gamma,
                gae_lambda,
                seed + 1000 * idx,
                idx,
                self._queue,
                engine=self._engine,
                weight_store=self._store,
                algo=algo,
            )

        self._make_runner = make_runner
        self._pipeline_depth = cfg.resolved_pipeline_depth(
            num_env_runners
        )
        self._runners: Dict[int, dict] = {}
        for idx in range(num_env_runners):
            handle = make_runner(idx)
            self._runners[idx] = {"handle": handle, "refs": deque()}
        if cfg.policy == "local":
            weights_ref = rt.put(params0)
            rt.get(
                [
                    st["handle"].set_weights.remote(weights_ref, 0)
                    for st in self._runners.values()
                ],
                timeout=120,
            )
        self._batches = self._device_prefetch(
            self._host_batches(), buffer_size=2
        )

    # -- runner pump ---------------------------------------------------
    def _submit(self, idx: int) -> None:
        state = self._runners[idx]
        state["refs"].append(
            state["handle"].sample_and_put.remote(
                epsilon=float(self._epsilon_fn(self._env_steps)),
            )
        )

    def _pump(self) -> None:
        """Top up every runner's call pipeline and fold finished
        fragments' counters in; a failed call (dead runner) drops its
        fragment, respawns the slot and re-syncs weights — the flow
        never stops for one actor."""
        rt = self._rt
        if self._stopped:
            return
        for idx, state in list(self._runners.items()):
            while len(state["refs"]) < self._pipeline_depth:
                self._submit(idx)
        heads = {
            state["refs"][0]: idx
            for idx, state in self._runners.items()
            if state["refs"]
        }
        if not heads:
            return
        ready, _ = rt.wait(
            list(heads), num_returns=len(heads), timeout=0.005
        )
        for ref in ready:
            idx = heads[ref]
            state = self._runners[idx]
            state["refs"].popleft()
            try:
                result = rt.get(ref, timeout=5)
            except Exception:
                # A dead POLICY ENGINE fails every runner the same
                # way; restoring runners against it would spin
                # forever — surface EngineDead to the caller fast
                # instead (never hang the learner loop).
                self._check_engine()
                self._restore_runner(idx)
                continue
            self._env_steps += int(result["env_steps"])
            self._waits_full += int(result.get("waits_full", 0))
            self._throttles += int(result.get("throttles", 0))
            if result["status"] == "ok":
                self._fragments_ok += 1
                self._frags_by_runner[idx] = (
                    self._frags_by_runner.get(idx, 0) + 1
                )
            else:
                self._fragments_dropped += 1
            self._recent_returns.extend(
                result.get("episode_returns") or []
            )
            self._recent_returns = self._recent_returns[-100:]
        self._observe_counters()

    def _check_engine(self) -> None:
        if self._engine is None:
            return
        from ..llm.engine import EngineDead

        try:
            stats = self._rt.get(
                self._engine.stats.remote(), timeout=10
            )
        except Exception as e:
            raise EngineDead(
                "policy engine actor is unreachable"
            ) from e
        if stats.get("dead"):
            raise EngineDead(
                "policy engine step loop died; rollout inference is "
                "down"
            )

    def _restore_runner(self, idx: int) -> None:
        """Prune-and-restore one dead slot: its in-flight fragments
        are lost (dropped, counted), the respawn re-syncs weights at
        the CURRENT version, and pumping resumes next pass."""
        rt = self._rt
        self._runner_failures += 1
        state = self._runners[idx]
        self._fragments_dropped += len(state["refs"]) + 1
        state["refs"].clear()
        try:
            rt.kill(state["handle"])
        except Exception:
            pass
        state["handle"] = self._make_runner(idx)
        if self.flow.policy == "local":
            try:
                ref = rt.put(self.learner.get_weights())
                rt.get(
                    state["handle"].set_weights.remote(
                        ref, self._version
                    ),
                    timeout=120,
                )
            except Exception:
                pass  # next restore attempt will retry

    # -- learner feed --------------------------------------------------
    def _host_batches(self):
        """Infinite generator of host training batches assembled from
        queue fragments. The stall waiting for runner data is billed
        to ``queue_wait_ms`` — the dataflow's analog of data_wait, so
        doctor/goodput attribute a learner starving on rollouts
        exactly like a trainer starving on input."""
        rt = self._rt
        from .._private import step_telemetry

        frag_rows = 0  # observed fragment size (rows)
        while True:
            frags: List[dict] = []
            rows = 0
            lag_floor: Optional[int] = None
            while rows < self._update_rows:
                self._pump()
                # Ask for just enough fragments to finish this batch:
                # overshooting (grab-everything) would grow the
                # training batch beyond update_rows and break the
                # updates-per-env-step parity with the synchronous
                # baseline the comparison rests on.
                want = (
                    max(
                        1,
                        -(-(self._update_rows - rows) // frag_rows),
                    )
                    if frag_rows else 2
                )
                with step_telemetry.phase_timer("queue_wait_ms"):
                    got = rt.get(
                        self._queue.get_batch.remote(want),
                        timeout=30,
                    )
                    if not got:
                        time.sleep(0.004)
                        continue
                    for frag in got:
                        payload = rt.get(
                            frag["item"]["ref"][0], timeout=30
                        )
                        frags.append(payload)
                        version = int(
                            frag["meta"].get("weight_version", 0)
                        )
                        lag_floor = (
                            version if lag_floor is None
                            else min(lag_floor, version)
                        )
                        n = len(payload[next(iter(payload))])
                        rows += n
                        frag_rows = max(frag_rows, n)
            batch = {
                k: np.concatenate([f[k] for f in frags])
                for k in frags[0]
            }
            lag = self._version - (
                lag_floor if lag_floor is not None else self._version
            )
            from .weight_sync import observe_weight_lag

            observe_weight_lag(lag, role="learner")
            batch["_weight_lag"] = lag
            yield batch

    def _device_prefetch(self, batches, buffer_size: int = 2):
        """The PR 4 prefetch pattern over queue batches: batch N+1's
        device_put dispatches before batch N trains (h2d billed per
        update), with the pull stall carried by `_host_batches`'s
        queue_wait timer instead of data_wait — same pipeline, the
        queue is the dataset."""
        import jax

        from .._private import step_telemetry

        window: deque = deque()
        iterator = iter(batches)
        # A host-ingesting learner (DQNLearner: the batch lands in a
        # host-side replay ring, minibatches upload separately) must
        # not pay an H2D+D2H round trip per batch — nor bill phantom
        # h2d_ms the doctor would misattribute.
        host_ingest = bool(getattr(self.learner, "host_ingest", False))

        def put(batch):
            if host_ingest:
                return batch
            t0 = time.monotonic()
            lag = batch.pop("_weight_lag", 0)
            out = {
                k: jax.device_put(v) for k, v in batch.items()
            }
            out["_weight_lag"] = lag
            step_telemetry.add_phase(
                "h2d_ms", (time.monotonic() - t0) * 1e3
            )
            return out

        while True:
            while len(window) < buffer_size:
                window.append(put(next(iterator)))
            yield window.popleft()

    # -- one learner update --------------------------------------------
    def train_update(self) -> Dict[str, Any]:
        """Consume one update's worth of fragments and take one
        learner update; publish weights per the sync interval. Emits
        a per-update step-telemetry record (queue_wait / h2d /
        weight_sync as stall phases, the update as step_ms)."""
        from .._private import step_telemetry
        from .weight_sync import push_weights

        t0 = time.monotonic()
        batch = next(self._batches)
        lag = int(batch.pop("_weight_lag", 0))
        # Top the runner mailboxes up RIGHT before the long update:
        # the fleet drains them back-to-back while the driver is
        # inside the jitted update — that is the overlap.
        self._pump()
        u0 = time.monotonic()
        metrics = self.learner.update(batch)
        update_ms = (time.monotonic() - u0) * 1e3
        self._pump()
        self._updates += 1
        self._version += 1
        if (
            self._updates % max(1, self.flow.sync_interval_updates)
            == 0
        ):
            with step_telemetry.phase_timer("weight_sync_ms"):
                self._last_sync_ms = push_weights(
                    self.learner.get_weights(),
                    self._version,
                    engines=(
                        [self._engine]
                        if self._engine is not None else []
                    ),
                    store=self._store,
                    queue=self._queue,
                )
        # Between publishes the queue's learner version deliberately
        # does NOT advance: the staleness gates compare against the
        # last PUBLISHED version — the freshest weights any runner
        # can possibly fetch. Advancing it per update would, at
        # sync_interval_updates > max_weight_lag + 1, throttle every
        # put against weights that do not exist yet and deadlock the
        # flow.
        self._observe_update()
        wall_ms = (time.monotonic() - t0) * 1e3
        step_telemetry.report_step(
            self._updates,
            rank=0,
            step_ms=update_ms,
            wall_ms=wall_ms,
            extra={"weight_version": self._version},
        )
        out = dict(metrics)
        out.update(
            weight_version=self._version,
            weight_lag=lag,
            weight_sync_ms=round(self._last_sync_ms, 3),
            update_ms=round(update_ms, 3),
        )
        return out

    # -- stats / lifecycle ---------------------------------------------
    def queue_stats(self) -> Dict[str, Any]:
        return self._rt.get(
            self._queue.stats.remote(), timeout=30
        )

    def engine_stats(self) -> Optional[Dict[str, Any]]:
        if self._engine is None:
            return None
        return self._rt.get(
            self._engine.stats.remote(), timeout=30
        )

    def stats(self) -> Dict[str, Any]:
        return {
            "env_steps": self._env_steps,
            "updates": self._updates,
            "weight_version": self._version,
            "fragments_ok": self._fragments_ok,
            "fragments_by_runner": dict(self._frags_by_runner),
            "fragments_dropped": self._fragments_dropped,
            "runner_failures": self._runner_failures,
            "waits_full": self._waits_full,
            "throttles": self._throttles,
            "last_weight_sync_ms": round(self._last_sync_ms, 3),
            "episode_return_mean": (
                float(np.mean(self._recent_returns))
                if self._recent_returns else float("nan")
            ),
        }

    def num_healthy_runners(self) -> int:
        rt = self._rt
        healthy = 0
        for state in self._runners.values():
            try:
                rt.get(state["handle"].ping.remote(), timeout=10)
                healthy += 1
            except Exception:
                pass
        return healthy

    def runner_handle(self, idx: int):
        return self._runners[idx]["handle"]

    def shutdown(self) -> None:
        self._stopped = True
        rt = self._rt
        for state in self._runners.values():
            try:
                rt.kill(state["handle"])
            except Exception:
                pass
        for handle in (self._engine, self._store, self._queue):
            if handle is not None:
                try:
                    rt.kill(handle)
                except Exception:
                    pass

    # -- metrics -------------------------------------------------------
    def _observe_counters(self) -> None:
        try:
            from ..util.metrics import Counter, Gauge

            global _ENV_STEPS, _STEPS_GAUGE
            if _ENV_STEPS is None:
                _ENV_STEPS = Counter(
                    "rl_env_steps_total",
                    description=(
                        "Environment steps sampled by the dataflow's "
                        "runner fleet"
                    ),
                    tag_keys=(),
                )
                _STEPS_GAUGE = Gauge(
                    "rl_env_steps",
                    description=(
                        "Environment steps sampled (driver view)"
                    ),
                    tag_keys=(),
                )
            delta = self._env_steps - getattr(
                self, "_env_steps_pushed", 0
            )
            if delta > 0:
                _ENV_STEPS.inc(float(delta))
                self._env_steps_pushed = self._env_steps
                _STEPS_GAUGE.set(float(self._env_steps))
        except Exception:
            pass

    def _observe_update(self) -> None:
        try:
            from ..util.metrics import Counter, Gauge

            global _UPDATES, _VERSION_GAUGE
            if _UPDATES is None:
                _UPDATES = Counter(
                    "rl_learner_updates_total",
                    description=(
                        "Learner updates taken by the dataflow"
                    ),
                    tag_keys=(),
                )
                _VERSION_GAUGE = Gauge(
                    "rl_weight_version",
                    description=(
                        "Latest policy-weight version published by "
                        "the learner"
                    ),
                    tag_keys=("store",),
                )
            _UPDATES.inc(1.0)
            _VERSION_GAUGE.set(
                float(self._version), tags={"store": "learner"}
            )
        except Exception:
            pass


_ENV_STEPS = None
_STEPS_GAUGE = None
_UPDATES = None
_VERSION_GAUGE = None
