"""Env runners: parallel rollout collection.

Reference: rllib/env/env_runner_group.py:70 + single_agent_env_runner
— a group of actor-hosted runners samples with the current policy and
returns batches; weights broadcast after each learner update. GAE is
computed runner-side at sample time (reference: ConnectorV2
GeneralAdvantageEstimation on the learner pipeline; moved here so the
learner consumes ready minibatches).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


def compute_gae(
    rew_buf: np.ndarray,  # [T, N]
    val_buf: np.ndarray,  # [T, N]
    done_buf: np.ndarray,  # [T, N] TERMINATIONS only
    last_values: np.ndarray,  # [N] bootstrap values of obs T
    gamma: float,
    lam: float,
) -> np.ndarray:
    """The GAE backward pass, shared by the synchronous runner and
    the decoupled dataflow runner (ISSUE 13: one copy of the math
    both comparison sides must agree on). Bootstraps through
    truncation but not termination — `done_buf` carries terminated
    flags only."""
    T, N = rew_buf.shape
    adv = np.zeros((T, N), np.float32)
    last_gae = np.zeros(N, np.float32)
    for t in reversed(range(T)):
        next_value = val_buf[t + 1] if t + 1 < T else last_values
        nonterminal = 1.0 - done_buf[t].astype(np.float32)
        delta = (
            rew_buf[t]
            + gamma * next_value * nonterminal
            - val_buf[t]
        )
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
    return adv


class SingleAgentEnvRunner:
    """Actor body: vectorized envs + CPU policy inference."""

    def __init__(
        self,
        env_spec,
        num_envs: int = 8,
        rollout_length: int = 64,
        gamma: float = 0.99,
        gae_lambda: float = 0.95,
        seed: int = 0,
    ):
        import jax

        from .env import VectorEnv, make_env

        self.vec = VectorEnv(
            lambda s: make_env(env_spec, seed=s), num_envs, seed=seed
        )
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.lam = gae_lambda
        self.params = None
        self._key = jax.random.PRNGKey(seed)
        self._obs = self.vec.reset()
        # Per-env accumulators for episode-return reporting.
        self._ep_returns = np.zeros(num_envs)
        self._finished_returns: List[float] = []

    def set_weights(self, params) -> bool:
        self.params = params
        return True

    def ping(self) -> str:
        return "ok"

    def sample(self) -> Dict[str, np.ndarray]:
        from .models import sample_actions

        assert self.params is not None, "set_weights first"
        T, N = self.rollout_length, self.vec.num_envs
        obs_buf = np.zeros((T, N, self._obs.shape[1]), np.float32)
        act_buf = np.zeros((T, N), np.int32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        for t in range(T):
            actions, logp, values, self._key = sample_actions(
                self.params, self._obs, self._key
            )
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = logp
            val_buf[t] = values
            next_obs, rewards, terminated, truncated = self.vec.step(
                actions
            )
            rew_buf[t] = rewards
            # GAE bootstraps through truncation but not termination.
            done_buf[t] = terminated
            self._ep_returns += rewards
            for i in range(N):
                if terminated[i] or truncated[i]:
                    self._finished_returns.append(
                        float(self._ep_returns[i])
                    )
                    self._ep_returns[i] = 0.0
            self._obs = next_obs
        _, _, last_values, self._key = sample_actions(
            self.params, self._obs, self._key
        )
        adv = compute_gae(
            rew_buf, val_buf, done_buf, last_values,
            self.gamma, self.lam,
        )
        returns = adv + val_buf
        flat = lambda a: a.reshape(-1, *a.shape[2:])  # noqa: E731
        episode_returns = self._finished_returns
        self._finished_returns = []
        return {
            "obs": flat(obs_buf),
            "actions": flat(act_buf),
            "logp": flat(logp_buf),
            "advantages": flat(adv),
            "value_targets": flat(returns),
            "episode_returns": np.asarray(episode_returns, np.float32),
        }


class EnvRunnerGroup:
    """Fault-tolerant fan-out over runner actors (reference:
    env_runner_group.py sample + weight sync, with
    rllib/utils/actor_manager.py:198 FaultTolerantActorManager
    underneath: a runner dying mid-iteration costs its shard of the
    sample, never the iteration; the dead slot is respawned and
    re-synced on the next sample)."""

    def __init__(
        self,
        env_spec,
        num_env_runners: int = 2,
        num_envs_per_runner: int = 8,
        rollout_length: int = 64,
        gamma: float = 0.99,
        gae_lambda: float = 0.95,
        seed: int = 0,
    ):
        import ray_tpu as rt

        from .actor_manager import FaultTolerantActorManager

        self._rt = rt
        self._latest_weights_ref = None
        runner_cls = rt.remote(num_cpus=1)(SingleAgentEnvRunner)

        def make_runner(i: int):
            return runner_cls.remote(
                env_spec,
                num_envs_per_runner,
                rollout_length,
                gamma,
                gae_lambda,
                seed + 1000 * i,
            )

        def restore_runner(_idx: int, handle) -> None:
            # A respawned runner holds no policy: re-sync before it
            # samples (reference: restored-worker weight sync).
            if self._latest_weights_ref is not None:
                rt.get(
                    handle.set_weights.remote(
                        self._latest_weights_ref
                    ),
                    timeout=120,
                )

        self.manager = FaultTolerantActorManager(
            [make_runner(i) for i in range(num_env_runners)],
            actor_factory=make_runner,
            on_restore=restore_runner,
        )

    @property
    def runners(self) -> List:
        return [
            self.manager.actor(idx)
            for idx in sorted(self.manager._actors)
        ]

    def num_healthy_runners(self) -> int:
        return self.manager.num_healthy_actors()

    def sync_weights(self, params) -> None:
        """Broadcast weights with ONE concurrent fan-out (the
        manager's rt.wait gather — no serial per-runner round-trips;
        ISSUE 13 satellite). A dead runner never fails the call: its
        slot is pruned from the healthy set and restored-and-resynced
        in the same pass (on_restore pushes this very ref)."""
        self._latest_weights_ref = self._rt.put(params)
        results = self.manager.foreach_actor(
            "set_weights", self._latest_weights_ref, timeout=120
        )
        if any(not r.ok for r in results):
            self.manager.probe_unhealthy_actors()

    def sample(self) -> Dict[str, np.ndarray]:
        # Heal dead slots from previous iterations first, then accept
        # whatever the healthy set returns this round.
        self.manager.probe_unhealthy_actors()
        results = self.manager.foreach_actor("sample", timeout=300)
        batches = self.manager.ok_values(results)
        if not batches:
            raise RuntimeError(
                "all env runners failed this iteration"
            )
        return {
            key: np.concatenate([b[key] for b in batches])
            for key in batches[0]
        }

    def shutdown(self) -> None:
        self.manager.shutdown()
