"""Developer tooling: static analysis for distributed correctness.

Four layers, one suppression/output contract (`# rt: noqa[RTxxx]`,
`--json`, exit 0/1/2):

* `ray_tpu lint [paths]` — per-file, syntactic (rules RT001-RT010 in
  devtools/rules.py; engine in devtools/lint.py). "Is this line an
  idiom this codebase has shipped bugs with?"
* `ray_tpu check [paths]` — whole-program, two-phase (symbol table in
  devtools/contracts.py; rules RT101-RT106 in devtools/check.py).
  "Do the two sides of this process boundary still agree?" —
  `.remote()` arity vs decorated signatures, `.options()` keys vs the
  shared option universe (`_private/options.py`), RPC call sites vs
  registered handlers and `wire.SCHEMAS`.
* `ray_tpu devtools race [paths]` — whole-program concurrency
  analysis (devtools/concurrency.py, rules RT201-RT206): execution
  contexts x shared attributes x lock discipline — data races,
  lock-order cycles, blocking-under-lock. Its runtime counterpart is
  devtools/lock_witness.py (`RT_lock_witness_enabled`), feeding
  `rt.diagnose()`'s `verdict.locks`.
* `ray_tpu devtools accel [paths]` — accelerator hot-path analysis
  (devtools/accel.py, rules RT301-RT306): jit/donate wrap inventory x
  hot-loop contexts — per-call re-jits, recompile-hazard arguments,
  hidden host syncs, use-after-donate, dispatch-only timing,
  compile-watch-invisible programs. Its runtime counterpart is
  `_private/compile_watch.py` (`rt.diagnose()`'s `verdict.compile`),
  and `accel.build_inventory()` is the bridge: a live recompile storm
  resolves its program name to the static RT302 site.
* `ray_tpu devtools all [paths]` — all four, merged, as one CI gate.

Every pass also audits the suppressions it owns (RT090/RT190/RT290/
RT390): a `# rt: noqa[RTxxx]` naming a nonexistent rule, or
suppressing one that never fires on that line, is itself a finding.

Programmatic:

    from ray_tpu.devtools import (
        lint_paths, check_paths, race_paths, accel_paths,
    )
    findings = (
        lint_paths(["ray_tpu"])
        + check_paths(["ray_tpu"])
        + race_paths(["ray_tpu"])
        + accel_paths(["ray_tpu"])
    )

The repo holds itself to all layers in tests/test_lint.py,
tests/test_check.py, tests/test_concurrency_analysis.py and
tests/test_accel_analysis.py, so every new idiom, cross-process
contract, thread/lock interaction, or accelerator hot path either
passes the rules or carries an explicit, reviewable suppression.
"""

from .accel import accel_paths, accel_sources, build_inventory  # noqa: F401
from .accel import main as accel_main  # noqa: F401
from .check import check_paths, check_sources  # noqa: F401
from .check import main as check_main  # noqa: F401
from .concurrency import race_paths, race_sources  # noqa: F401
from .concurrency import main as race_main  # noqa: F401
from .lint import Finding, lint_paths, lint_source, main  # noqa: F401
from .rules import ALL_RULES  # noqa: F401


def all_main(argv=None, out=None) -> int:
    """`ray_tpu devtools all [paths] [--json]` — lint + check + race +
    accel over the same tree with merged findings: the single CI gate.
    Shares the individual tools' default-path, validation, rendering,
    and exit-code behavior (0 clean, 1 findings, 2 usage errors) so
    the gate can never diverge from running them separately."""
    import argparse
    import json as _json
    import os
    import sys
    from dataclasses import asdict

    out = out if out is not None else sys.stdout
    parser = argparse.ArgumentParser(
        prog="ray_tpu devtools all",
        description=(
            "lint + check + race + accel with merged findings "
            "(single CI gate)"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", help="files/dirs (default: ray_tpu)"
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit merged findings as JSON (CI mode)",
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0
    # Same default as lint/check main(): the package this CLI shipped
    # in, never a cwd-relative "ray_tpu".
    paths = args.paths or [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(
            f"devtools: no such path(s): {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2
    findings = (
        lint_paths(paths)
        + check_paths(paths)
        + race_paths(paths)
        + accel_paths(paths)
    )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if args.as_json:
        print(
            _json.dumps([asdict(f) for f in findings], indent=2),
            file=out,
        )
    else:
        for finding in findings:
            print(finding.render(), file=out)
        if findings:
            print(f"{len(findings)} finding(s)", file=out)
    return 1 if findings else 0
