"""Developer tooling: the distributed-correctness linter.

`ray_tpu lint [paths]` (scripts/cli.py) or programmatic:

    from ray_tpu.devtools import lint_paths
    findings = lint_paths(["ray_tpu"])

Rules RT001-RT008 live in devtools/rules.py; the engine (single AST
walk per file, `# rt: noqa[RTxxx]` suppressions, JSON output) in
devtools/lint.py. The repo lints itself in tests/test_lint.py, so
every new framework idiom either passes the rules or carries an
explicit, reviewable suppression.
"""

from .lint import Finding, lint_paths, lint_source, main  # noqa: F401
from .rules import ALL_RULES  # noqa: F401
