"""Developer tooling: static analysis for distributed correctness.

Three layers, one suppression/output contract (`# rt: noqa[RTxxx]`,
`--json`, exit 0/1/2):

* `ray_tpu lint [paths]` — per-file, syntactic (rules RT001-RT010 in
  devtools/rules.py; engine in devtools/lint.py). "Is this line an
  idiom this codebase has shipped bugs with?"
* `ray_tpu check [paths]` — whole-program, two-phase (symbol table in
  devtools/contracts.py; rules RT101-RT106 in devtools/check.py).
  "Do the two sides of this process boundary still agree?" —
  `.remote()` arity vs decorated signatures, `.options()` keys vs the
  shared option universe (`_private/options.py`), RPC call sites vs
  registered handlers and `wire.SCHEMAS`.
* `ray_tpu devtools race [paths]` — whole-program concurrency
  analysis (devtools/concurrency.py, rules RT201-RT206): execution
  contexts x shared attributes x lock discipline — data races,
  lock-order cycles, blocking-under-lock. Its runtime counterpart is
  devtools/lock_witness.py (`RT_lock_witness_enabled`), feeding
  `rt.diagnose()`'s `verdict.locks`.
* `ray_tpu devtools all [paths]` — all three, merged, as one CI gate.

Programmatic:

    from ray_tpu.devtools import lint_paths, check_paths, race_paths
    findings = (
        lint_paths(["ray_tpu"])
        + check_paths(["ray_tpu"])
        + race_paths(["ray_tpu"])
    )

The repo holds itself to all layers in tests/test_lint.py,
tests/test_check.py and tests/test_concurrency_analysis.py, so every
new idiom, cross-process contract, or thread/lock interaction either
passes the rules or carries an explicit, reviewable suppression.
"""

from .check import check_paths, check_sources  # noqa: F401
from .check import main as check_main  # noqa: F401
from .concurrency import race_paths, race_sources  # noqa: F401
from .concurrency import main as race_main  # noqa: F401
from .lint import Finding, lint_paths, lint_source, main  # noqa: F401
from .rules import ALL_RULES  # noqa: F401


def all_main(argv=None, out=None) -> int:
    """`ray_tpu devtools all [paths] [--json]` — lint + check + race
    over the same tree with merged findings: the single CI gate.
    Shares the individual tools' default-path, validation, rendering,
    and exit-code behavior (0 clean, 1 findings, 2 usage errors) so
    the gate can never diverge from running them separately."""
    import argparse
    import json as _json
    import os
    import sys
    from dataclasses import asdict

    out = out if out is not None else sys.stdout
    parser = argparse.ArgumentParser(
        prog="ray_tpu devtools all",
        description=(
            "lint + check + race with merged findings (single CI gate)"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", help="files/dirs (default: ray_tpu)"
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit merged findings as JSON (CI mode)",
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0
    # Same default as lint/check main(): the package this CLI shipped
    # in, never a cwd-relative "ray_tpu".
    paths = args.paths or [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(
            f"devtools: no such path(s): {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2
    findings = lint_paths(paths) + check_paths(paths) + race_paths(paths)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if args.as_json:
        print(
            _json.dumps([asdict(f) for f in findings], indent=2),
            file=out,
        )
    else:
        for finding in findings:
            print(finding.render(), file=out)
        if findings:
            print(f"{len(findings)} finding(s)", file=out)
    return 1 if findings else 0
