"""Phase 1 of the whole-program contract checker: the symbol table.

`ray_tpu lint` (rules.py) is deliberately single-file and syntactic;
the contract bugs that survive it are *cross-program*: a `.remote()`
call whose arity drifted from the decorated signature, an `.options()`
key the submission path silently ignores, a `client.call("m", ...)`
site naming a handler that no server registers, call-site kwargs that
no longer match the method's `wire.SCHEMAS` entry. Those need one pass
that sees every file before any file is judged.

This module builds that view. `build_symbol_table(paths)` parses every
source file once and extracts:

* every ``@rt.remote`` function and actor class, with its resolved
  signature (positional/keyword/defaults/varargs) and decorator
  options;
* every RPC handler registration — explicit ``server.register("m",
  fn)`` string literals AND the daemon's registration-loop idiom
  (``for name in ["a", "b", ...]: server.register(name,
  getattr(self, "_h_" + name))``);
* every ``wire.SCHEMAS``-style per-method argument schema (a module
  assigning ``SCHEMAS = {"method": {"field": type, ...}, ...}``);
* every RPC call site (``.call/.notify/.call_async("m", ...)`` with a
  string-literal method) and its keyword names;
* per-module name bindings (decorated defs + ``from x import y``)
  so phase 2 (check.py) can resolve receivers;
* a *liveness witness* set: every other string constant in the tree
  equal to some handler name (a method dispatched dynamically —
  ``_bundle_call(nid, "prepare_bundle", ...)`` — is alive even though
  no literal ``.call("prepare_bundle")`` exists).

The option-key universe is NOT re-derived here: it is imported from
``ray_tpu._private.options`` — the same table the runtime validator
enforces, so the static and runtime halves of RT102 can never drift
from each other.

Parsed sources and per-file noqa maps ride along in the table so
phase 2 walks each tree exactly once more without re-reading files.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .lint import _dotted, _is_remote_decorator, _parse_noqa

#: RPC client verbs whose first string-literal argument names a wire
#: method, mapped to the client-side kwargs that never reach the
#: handler (RpcClient.call(method, timeout=..., retries=..., **kwargs)).
RPC_VERBS: Dict[str, frozenset] = {
    "call": frozenset({"timeout", "retries"}),
    "call_async": frozenset({"callback"}),
    "notify": frozenset(),
}


# ---------------------------------------------------------------------------
# signatures
# ---------------------------------------------------------------------------


@dataclass
class Signature:
    """Callable shape, reduced to what arity checking needs."""

    params: List[str]  # posonly + positional-or-keyword, in order
    posonly: int  # first `posonly` of params are positional-only
    defaults: int  # trailing params carrying defaults
    kwonly: Dict[str, bool]  # name -> has default
    vararg: bool
    kwarg: bool

    @property
    def required_positional(self) -> int:
        return len(self.params) - self.defaults

    def keyword_names(self) -> Set[str]:
        return set(self.params[self.posonly:]) | set(self.kwonly)


def signature_of(node, skip_first: bool = False) -> Signature:
    """Signature from an ast.FunctionDef/AsyncFunctionDef; `skip_first`
    drops the bound receiver (self/cls) for methods."""
    a = node.args
    posonly = [p.arg for p in a.posonlyargs]
    pos = [p.arg for p in a.args]
    params = posonly + pos
    n_posonly = len(posonly)
    if skip_first and params:
        params = params[1:]
        n_posonly = max(0, n_posonly - 1)
    defaults = len(a.defaults)
    kwonly = {
        p.arg: d is not None
        for p, d in zip(a.kwonlyargs, a.kw_defaults)
    }
    return Signature(
        params=params,
        posonly=n_posonly,
        defaults=min(defaults, len(params)),
        kwonly=kwonly,
        vararg=a.vararg is not None,
        kwarg=a.kwarg is not None,
    )


# ---------------------------------------------------------------------------
# symbols
# ---------------------------------------------------------------------------


@dataclass
class RemoteFunc:
    name: str
    path: str
    lineno: int
    sig: Signature
    options: Dict[str, ast.expr]  # decorator keyword options


@dataclass
class RemoteActor:
    name: str
    path: str
    lineno: int
    init: Signature  # __init__ minus self ((), no-arg if absent)
    methods: Dict[str, Signature]
    options: Dict[str, ast.expr]
    #: True when the class has any base besides `object`: inherited
    #: methods are invisible to the body scan, so unknown-method
    #: judgments must stay silent (precision over recall).
    has_bases: bool = False


@dataclass
class Handler:
    method: str
    path: str
    lineno: int


@dataclass
class CallSite:
    method: str
    path: str
    lineno: int
    col: int
    verb: str  # call | notify | call_async
    kwargs: Set[str]
    has_star_kwargs: bool


@dataclass
class SchemaField:
    optional: bool
    #: Accepted python types, or None when the spec expression could
    #: not be resolved statically (treated as "any").
    types: Optional[Tuple[type, ...]]


@dataclass
class ParsedFile:
    path: str
    source: str
    tree: ast.Module
    noqa: Dict[int, Optional[set]]


@dataclass
class SymbolTable:
    files: List[ParsedFile] = field(default_factory=list)
    #: (path, name) -> symbol, plus import-resolved aliases.
    bindings: Dict[str, Dict[str, object]] = field(default_factory=dict)
    functions_by_name: Dict[str, List[RemoteFunc]] = field(
        default_factory=dict
    )
    actors_by_name: Dict[str, List[RemoteActor]] = field(
        default_factory=dict
    )
    handlers: Dict[str, List[Handler]] = field(default_factory=dict)
    call_sites: List[CallSite] = field(default_factory=list)
    schemas: Dict[str, Dict[str, SchemaField]] = field(
        default_factory=dict
    )
    #: String constants seen OUTSIDE registration/schema contexts —
    #: dynamic-dispatch liveness witnesses for the dead-handler rule.
    witnesses: Set[str] = field(default_factory=set)
    #: (path, lineno) -> symbol defined at that site. Phase 2 binds
    #: these scope-aware as it walks, so two test functions each
    #: defining `@rt.remote class A` resolve to THEIR A, not the
    #: file's last one.
    by_def: Dict[Tuple[str, int], object] = field(default_factory=dict)
    #: (path, name) import edges resolved after all files parse.
    _imports: List[Tuple[str, str, str]] = field(default_factory=list)

    def resolve(self, path: str, name: str):
        """Receiver name -> RemoteFunc/RemoteActor, or None. Module
        bindings win; an unambiguous global name resolves anywhere
        (whole-program fallback for receivers built elsewhere)."""
        sym = self.bindings.get(path, {}).get(name)
        if sym is not None:
            return sym
        funcs = self.functions_by_name.get(name, [])
        actors = self.actors_by_name.get(name, [])
        if len(funcs) + len(actors) == 1:
            return (funcs or actors)[0]
        return None


# ---------------------------------------------------------------------------
# schema-expression decoding
# ---------------------------------------------------------------------------

_TYPE_NAMES = {
    "str": str,
    "int": int,
    "float": float,
    "bool": bool,
    "bytes": bytes,
    "dict": dict,
    "list": list,
    "tuple": tuple,
    "_num": (int, float),
}


def _decode_type_expr(node: ast.expr) -> Optional[Tuple[type, ...]]:
    """Schema value expression -> accepted-type tuple, or None for
    "couldn't resolve; accept anything". Handles the registry's
    idioms: bare names, `_num`, `type(None)`, tuples, and `+`-joined
    tuples."""

    def one(n) -> Optional[tuple]:
        if isinstance(n, ast.Name):
            t = _TYPE_NAMES.get(n.id)
            if t is None:
                return None
            return t if isinstance(t, tuple) else (t,)
        # type(None)
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Name)
            and n.func.id == "type"
            and len(n.args) == 1
            and isinstance(n.args[0], ast.Constant)
            and n.args[0].value is None
        ):
            return (type(None),)
        if isinstance(n, ast.Tuple):
            out: tuple = ()
            for element in n.elts:
                part = one(element)
                if part is None:
                    return None
                out += part
            return out
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Add):
            left, right = one(n.left), one(n.right)
            if left is None or right is None:
                return None
            return left + right
        return None

    return one(node)


# ---------------------------------------------------------------------------
# per-file extraction
# ---------------------------------------------------------------------------


class _FileScanner(ast.NodeVisitor):
    """One walk per file collecting every phase-1 fact."""

    def __init__(self, path: str, table: SymbolTable):
        self.path = path
        self.table = table
        self.bindings = table.bindings.setdefault(path, {})
        #: Constant nodes consumed by registration lists / register()
        #: first args / schema keys — excluded from liveness witnesses.
        self._consumed: Set[int] = set()
        self._strings: List[str] = []
        self._class_depth = 0

    # -- decorated defs ------------------------------------------------
    def _decorator_options(self, node) -> Dict[str, ast.expr]:
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and _is_remote_decorator(dec):
                return {
                    kw.arg: kw.value
                    for kw in dec.keywords
                    if kw.arg is not None
                }
        return {}

    def visit_FunctionDef(self, node, async_=False):
        if self._class_depth == 0 and any(
            _is_remote_decorator(d) for d in node.decorator_list
        ):
            sym = RemoteFunc(
                name=node.name,
                path=self.path,
                lineno=node.lineno,
                sig=signature_of(node),
                options=self._decorator_options(node),
            )
            self.table.functions_by_name.setdefault(
                node.name, []
            ).append(sym)
            self.bindings[node.name] = sym
            self.table.by_def[(self.path, node.lineno)] = sym
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef):
        if any(_is_remote_decorator(d) for d in node.decorator_list):
            init = Signature([], 0, 0, {}, False, False)
            methods: Dict[str, Signature] = {}
            for item in node.body:
                if not isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                is_static = any(
                    isinstance(d, ast.Name) and d.id == "staticmethod"
                    for d in item.decorator_list
                )
                sig = signature_of(item, skip_first=not is_static)
                if item.name == "__init__":
                    init = sig
                elif not item.name.startswith("_"):
                    methods[item.name] = sig
            has_bases = any(
                not (isinstance(b, ast.Name) and b.id == "object")
                for b in node.bases
            )
            sym = RemoteActor(
                name=node.name,
                path=self.path,
                lineno=node.lineno,
                init=init,
                methods=methods,
                options=self._decorator_options(node),
                has_bases=has_bases,
            )
            self.table.actors_by_name.setdefault(node.name, []).append(
                sym
            )
            self.bindings[node.name] = sym
            self.table.by_def[(self.path, node.lineno)] = sym
        self._class_depth += 1
        self.generic_visit(node)
        self._class_depth -= 1

    # -- imports -------------------------------------------------------
    def visit_ImportFrom(self, node: ast.ImportFrom):
        for alias in node.names:
            local = alias.asname or alias.name
            # Resolved after every file is parsed (the target may not
            # have been scanned yet).
            self.table._imports.append((self.path, local, alias.name))
        self.generic_visit(node)

    # -- handlers, call sites, schemas ---------------------------------
    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if (
                attr == "register"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                lit = node.args[0]
                self._consumed.add(id(lit))
                self.table.handlers.setdefault(lit.value, []).append(
                    Handler(lit.value, self.path, lit.lineno)
                )
            elif (
                attr in RPC_VERBS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                self.table.call_sites.append(
                    CallSite(
                        method=node.args[0].value,
                        path=self.path,
                        lineno=node.lineno,
                        col=node.col_offset + 1,
                        verb=attr,
                        kwargs={
                            kw.arg
                            for kw in node.keywords
                            if kw.arg is not None
                        },
                        has_star_kwargs=any(
                            kw.arg is None for kw in node.keywords
                        ),
                    )
                )
        self.generic_visit(node)

    def visit_For(self, node: ast.For):
        # Registration-loop idiom: for name in ["a", ...]:
        #     server.register(name, getattr(self, "_h_" + name))
        if (
            isinstance(node.target, ast.Name)
            and isinstance(node.iter, (ast.List, ast.Tuple))
            and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in node.iter.elts
            )
        ):
            target = node.target.id
            registers = any(
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "register"
                and sub.args
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id == target
                for stmt in node.body
                for sub in ast.walk(stmt)
            )
            if registers:
                for element in node.iter.elts:
                    self._consumed.add(id(element))
                    self.table.handlers.setdefault(
                        element.value, []
                    ).append(
                        Handler(
                            element.value, self.path, element.lineno
                        )
                    )
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        # SCHEMAS = {"method": {"field": type, ...}, ...}
        if (
            any(
                isinstance(t, ast.Name) and t.id == "SCHEMAS"
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            for key, value in zip(node.value.keys, node.value.values):
                if not (
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and isinstance(value, ast.Dict)
                ):
                    continue
                self._consumed.add(id(key))
                fields: Dict[str, SchemaField] = {}
                for fk, fv in zip(value.keys, value.values):
                    if not (
                        isinstance(fk, ast.Constant)
                        and isinstance(fk.value, str)
                    ):
                        continue
                    raw = fk.value
                    optional = raw.startswith("?")
                    fields[raw[1:] if optional else raw] = SchemaField(
                        optional=optional,
                        types=_decode_type_expr(fv),
                    )
                self.table.schemas[key.value] = fields
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant):
        if isinstance(node.value, str) and id(node) not in self._consumed:
            self._strings.append(node.value)

    def finish(self):
        # Witnesses are filtered against handler names later (cheap
        # set intersection once every file contributed its handlers).
        self.table.witnesses.update(self._strings)


# ---------------------------------------------------------------------------
# table construction
# ---------------------------------------------------------------------------


def build_symbol_table(
    sources: Sequence[Tuple[str, str]],
) -> SymbolTable:
    """`sources` is a list of (path, source-text). Unparseable files
    are skipped here — phase 2 reports them as RT000 findings."""
    table = SymbolTable()
    scanners: List[_FileScanner] = []
    for path, source in sources:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue
        table.files.append(
            ParsedFile(
                path=path,
                source=source,
                tree=tree,
                noqa=_parse_noqa(source),
            )
        )
        scanner = _FileScanner(path, table)
        scanner.visit(tree)
        scanners.append(scanner)
    for scanner in scanners:
        # Register-call sites were consumed during the walk; Constant
        # visits may have run before the consuming Call visit in
        # sibling order, so re-filter now that _consumed is complete.
        scanner._strings = [
            s for s in scanner._strings if s  # keep non-empty only
        ]
        scanner.finish()
    # Import-edge resolution: bind `from x import y` names to the
    # (unique) symbol named y anywhere in the analyzed tree. Ambiguous
    # names stay unbound — precision over recall.
    for path, local, orig in table._imports:
        funcs = table.functions_by_name.get(orig, [])
        actors = table.actors_by_name.get(orig, [])
        if len(funcs) + len(actors) == 1:
            table.bindings.setdefault(path, {}).setdefault(
                local, (funcs or actors)[0]
            )
    return table
