"""The RT001–RT010 distributed-correctness passes.

Each rule is one bug class ray_tpu has actually shipped (or nearly
shipped — see ADVICE.md for the originals) generalized into a
syntactic pattern plus a path scope. Rules are deliberately
high-precision: a pass that cries wolf on idiomatic code gets noqa'd
into silence, so each one matches the narrow framework idiom and
leaves the rest of Python alone.

| id    | bug class                                                    |
|-------|--------------------------------------------------------------|
| RT001 | blocking ray_tpu.get() inside actor methods / async bodies   |
| RT002 | payload-equality dedup of retryable channel/rpc records      |
| RT003 | wall-clock / RNG nondeterminism on replayable wire paths     |
| RT004 | thread/lock/socket creation at import time (fork-unsafe)     |
| RT005 | unvalidated int() narrowing of public-API numeric params     |
| RT006 | hardcoded namespace="default" outside the session module     |
| RT007 | bare/swallowed exceptions in daemon RPC handlers             |
| RT008 | cross-process wait()/join() with no timeout                  |
| RT009 | metric names/labels violating the Prometheus convention      |
| RT010 | unbounded-cardinality metric labels (per-request/object ids) |

Hooks a rule may define (all optional): ``on_call``, ``on_compare``,
``on_except``, ``on_assign``, ``on_keyword``, ``on_functiondef`` —
each ``(node, ctx) -> iterable of (message, anchor_node | None)``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional, Tuple

from .lint import LintContext, _dotted

Hit = Tuple[str, Optional[ast.AST]]


class Rule:
    id: str = "RT000"
    title: str = ""
    #: Substrings of the normalized path; None = every file.
    include: Optional[Tuple[str, ...]] = None
    exclude: Tuple[str, ...] = ()
    exclude_suffixes: Tuple[str, ...] = ()

    def in_scope(self, norm_path: str) -> bool:
        if any(s in norm_path for s in self.exclude):
            return False
        if any(norm_path.endswith(s) for s in self.exclude_suffixes):
            return False
        if self.include is None:
            return True
        return any(s in norm_path for s in self.include)


def _terminal_name(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


class BlockingGetInActor(Rule):
    """RT001: `ray_tpu.get()` blocks the calling thread until another
    task finishes. Inside an actor method it wedges the actor's
    (bounded-concurrency) executor; inside `async def` it starves the
    shared event loop — both are distributed deadlocks waiting for the
    right load. Use `await ref` (async) or restructure so the driver
    joins results."""

    id = "RT001"
    title = "blocking ray_tpu.get() inside actor method or async def"
    exclude = ("tests/",)

    _GET_CALLEES = ("ray_tpu.get", "rt.get", "ray_tpu.wait", "rt.wait")

    def on_call(self, node: ast.Call, ctx: LintContext) -> Iterable[Hit]:
        name = _dotted(node.func)
        if name not in self._GET_CALLEES:
            return
        if ctx.current_func is None:
            return
        if ctx.in_async_func:
            yield (
                f"blocking {name}() inside `async def "
                f"{ctx.current_func.name}` starves the actor event loop; "
                "await the ref instead",
                None,
            )
        elif ctx.in_actor_class:
            yield (
                f"blocking {name}() inside actor method "
                f"`{ctx.current_func.name}` can deadlock the actor's "
                "bounded executor; resolve refs on the driver or pass "
                "values in",
                None,
            )


class PayloadEqualityDedup(Rule):
    """RT002: deduplicating a retried record by comparing payload
    bytes treats *distinct* records with equal bytes as retries (two
    execute() calls with the same input) and silently drops one — the
    tcp_channel.py bug class. Retry identity must be a sequence
    number / explicit token framed with the record, never content."""

    id = "RT002"
    title = "payload-equality dedup of retryable records"
    include = ("dag/", "channel", "rpc.py", "wire.py")
    exclude = ("tests/",)

    _MARKERS = ("payload", "frame")

    def on_compare(self, node: ast.Compare, ctx: LintContext) -> Iterable[Hit]:
        if not any(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            return
        for side in (node.left, *node.comparators):
            name = _terminal_name(side).lower()
            if any(marker in name for marker in self._MARKERS):
                yield (
                    f"equality comparison on raw record bytes "
                    f"(`{_terminal_name(side)}`) — retries must be "
                    "identified by a per-channel sequence number, not "
                    "payload equality",
                    None,
                )
                return


class WireNondeterminism(Rule):
    """RT003: wire-protocol and replayable paths (compiled-DAG
    channels, frame codec, workflow replay) must produce identical
    bytes/decisions across a re-execution; wall clocks and RNGs break
    resume and cross-process agreement silently."""

    id = "RT003"
    title = "nondeterminism (time.time/random/os.urandom) on replayable path"
    include = ("dag/", "wire.py", "workflow/")
    exclude = ("tests/",)

    def on_call(self, node: ast.Call, ctx: LintContext) -> Iterable[Hit]:
        name = _dotted(node.func)
        if (
            name == "time.time"
            or name == "os.urandom"
            or name.startswith("random.")
        ):
            yield (
                f"{name}() on a replayable/wire path — a re-executed "
                "step must reproduce the original bytes; derive values "
                "from the record/step identity instead",
                None,
            )


class ImportTimeForkHazard(Rule):
    """RT004: modules pre-imported by the worker fork-server template
    (worker_forkserver.py) execute at import time in the template;
    threads/locks/sockets created there are shared copy-on-write with
    every forked worker — a thread doesn't survive fork, a lock held
    at fork deadlocks children, an fd is shared. Create them lazily
    (first use) instead."""

    id = "RT004"
    title = "thread/lock/socket created at import time in forkserver module"
    include = ("_private/", "_native/")
    exclude = ("tests/",)

    _THREADING = (
        "Thread",
        "Timer",
        "Lock",
        "RLock",
        "Condition",
        "Event",
        "Semaphore",
        "BoundedSemaphore",
        "Barrier",
    )
    _SOCKET = ("socket", "create_connection", "socketpair")

    def on_call(self, node: ast.Call, ctx: LintContext) -> Iterable[Hit]:
        if not ctx.at_import_time:
            return
        name = _dotted(node.func)
        flagged = (
            name in tuple(f"threading.{n}" for n in self._THREADING)
            or name in tuple(f"socket.{n}" for n in self._SOCKET)
        )
        if flagged:
            yield (
                f"{name}() at import time in a fork-server-loaded "
                "module; forked workers inherit it copy-on-write — "
                "create it lazily on first use",
                None,
            )


class UnvalidatedNarrowing(Rule):
    """RT005: `int(x)` on a user-supplied public-API parameter
    silently truncates 2.5 -> 2 (the autoscaler sdk bug). Validate
    first (`x.is_integer()`, `x != int(x)`, or an isinstance gate)
    or take the truncation out of the API."""

    id = "RT005"
    title = "unvalidated int() narrowing of a public-API parameter"
    exclude = ("_private/", "_native/", "tests/", "devtools/")

    def on_call(self, node: ast.Call, ctx: LintContext) -> Iterable[Hit]:
        func = ctx.current_func
        if func is None or func.name.startswith("_"):
            return
        if not (
            isinstance(node.func, ast.Name)
            and node.func.id == "int"
            and len(node.args) == 1
            and not node.keywords
            and isinstance(node.args[0], ast.Name)
        ):
            return
        param = node.args[0].id
        annotations = {
            a.arg: a.annotation
            for a in (
                *func.args.posonlyargs,
                *func.args.args,
                *func.args.kwonlyargs,
            )
        }
        if param not in annotations:
            return  # a local, not caller input
        annotation = annotations[param]
        if isinstance(annotation, ast.Name) and annotation.id == "int":
            return  # declared int; int(x) is a no-op normalization
        if self._has_validation(func, param, node):
            return
        yield (
            f"int({param}) truncates fractional caller input in public "
            f"API `{func.name}`; validate {param} is integral first",
            None,
        )

    @staticmethod
    def _has_validation(func: ast.AST, param: str, site: ast.Call) -> bool:
        for sub in ast.walk(func):
            if sub is site:
                continue
            # x.is_integer()
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "is_integer"
                and _terminal_name(sub.func.value) == param
            ):
                return True
            # x != int(x)  /  int(x) == x
            if isinstance(sub, ast.Compare):
                names = set()
                casts = set()
                for side in (sub.left, *sub.comparators):
                    if isinstance(side, ast.Name):
                        names.add(side.id)
                    if (
                        isinstance(side, ast.Call)
                        and isinstance(side.func, ast.Name)
                        and side.func.id == "int"
                        and len(side.args) == 1
                        and isinstance(side.args[0], ast.Name)
                    ):
                        casts.add(side.args[0].id)
                if param in names and param in casts:
                    return True
            # isinstance(x, int) / isinstance(x, (int, ...))
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "isinstance"
                and len(sub.args) == 2
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id == param
            ):
                types = sub.args[1]
                elements = (
                    types.elts if isinstance(types, ast.Tuple) else [types]
                )
                if any(
                    isinstance(e, ast.Name) and e.id == "int"
                    for e in elements
                ):
                    return True
        return False


class HardcodedNamespace(Rule):
    """RT006: a literal "default" namespace outside the session-
    context module (ray_tpu/api.py) pins lookups to the wrong
    namespace for any driver that called init(namespace=...) — the
    worker.py bug class. Resolve through the session context; daemon-
    side wire-compat fallbacks carry an explicit noqa."""

    id = "RT006"
    title = 'hardcoded namespace="default" outside the session module'
    exclude = ("tests/",)
    exclude_suffixes = ("ray_tpu/api.py",)

    @staticmethod
    def _is_default(node: ast.AST) -> bool:
        return isinstance(node, ast.Constant) and node.value == "default"

    def on_keyword(self, node: ast.keyword, ctx: LintContext) -> Iterable[Hit]:
        if node.arg == "namespace" and self._is_default(node.value):
            yield (
                'namespace="default" literal pins the session namespace; '
                "resolve it from the session/job context",
                node.value,
            )

    def on_assign(self, node: ast.Assign, ctx: LintContext) -> Iterable[Hit]:
        if not self._is_default(node.value):
            return
        for target in node.targets:
            if _terminal_name(target) == "namespace":
                yield (
                    'namespace = "default" literal pins the session '
                    "namespace; resolve it from the session/job context",
                    None,
                )
                return

    def on_call(self, node: ast.Call, ctx: LintContext) -> Iterable[Hit]:
        # spec.get("namespace", "default") — wire-compat fallback shape
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and len(node.args) == 2
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == "namespace"
            and self._is_default(node.args[1])
        ):
            yield (
                '.get("namespace", "default") falls back to the literal '
                "default namespace; resolve through the session/job "
                "context (or noqa a deliberate wire-compat fallback)",
                None,
            )


class SwallowedHandlerError(Rule):
    """RT007: in daemon RPC dispatch, a bare `except:` (catches
    KeyboardInterrupt/SystemExit too) or an `except Exception: pass`
    inside a handler silently converts protocol bugs into hangs at
    the caller — the error never reaches a reply frame. Reply with a
    typed error instead."""

    id = "RT007"
    title = "bare/swallowed exception in daemon RPC handler"
    include = ("daemon", "rpc")
    exclude = ("tests/",)

    def on_except(
        self, node: ast.ExceptHandler, ctx: LintContext
    ) -> Iterable[Hit]:
        if node.type is None:
            yield (
                "bare `except:` in RPC-plane code catches SystemExit/"
                "KeyboardInterrupt; catch Exception (and reply with an "
                "error) instead",
                None,
            )
            return
        func = ctx.current_func
        in_handler = func is not None and (
            func.name.startswith("_h_") or func.name.startswith("handle")
        )
        swallows = (
            len(node.body) == 1
            and isinstance(node.body[0], ast.Pass)
            and isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException")
        )
        if in_handler and swallows:
            yield (
                f"`except {node.type.id}: pass` inside RPC handler "
                f"`{func.name}` drops the error — the caller hangs or "
                "sees a timeout instead of the cause; reply with an "
                "error payload",
                None,
            )


class MissingWaitTimeout(Rule):
    """RT008: a cross-process `.wait()` / `.join()` with no timeout
    turns a dead peer into an infinite hang. Every cross-process wait
    needs a deadline (or an explicit noqa stating why parking forever
    is safe)."""

    id = "RT008"
    title = "cross-process wait()/join() without a timeout"
    exclude = ("tests/",)

    def on_call(self, node: ast.Call, ctx: LintContext) -> Iterable[Hit]:
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in ("wait", "join"):
            return
        if node.args or node.keywords:
            return
        yield (
            f".{node.func.attr}() with no timeout waits forever if the "
            "peer died; pass a deadline (or noqa a deliberate park)",
            None,
        )


class MetricNamingConvention(Rule):
    """RT009: exported metric series must stay Prometheus-legal and
    follow the documented convention (README "Metrics export"):
    snake_case ``^[a-z][a-z0-9_]*$`` names, counters ending in
    ``_total``, snake_case label keys. Dots/dashes only survive
    because the exposition layer sanitizes them — two sanitized-equal
    names would silently merge into one series, so the linter rejects
    them at the declaration site instead. Scope: metrics DECLARED in
    the package (tests may name throwaway metrics freely)."""

    id = "RT009"
    title = "metric name/label violates the naming convention"
    exclude = ("tests/",)

    _CONSTRUCTORS = ("Counter", "Gauge", "Histogram")
    _NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

    def _literal_name(self, node: ast.Call):
        """The metric name argument when it is a string literal:
        first positional or `name=` keyword."""
        if node.args and isinstance(node.args[0], ast.Constant):
            if isinstance(node.args[0].value, str):
                return node.args[0].value, node.args[0]
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                if isinstance(kw.value.value, str):
                    return kw.value.value, kw.value
        return None, None

    def on_call(self, node: ast.Call, ctx: LintContext) -> Iterable[Hit]:
        kind = _terminal_name(node.func)
        if kind not in self._CONSTRUCTORS:
            return
        name, anchor = self._literal_name(node)
        if name is None:
            return
        if not self._NAME_RE.match(name):
            yield (
                f"metric name {name!r} violates the convention "
                "^[a-z][a-z0-9_]*$ (sanitized-equal names merge into "
                "one exported series)",
                anchor,
            )
        elif kind == "Counter" and not name.endswith("_total"):
            yield (
                f"counter {name!r} must end in `_total` (Prometheus "
                "counter convention; rate() readers depend on it)",
                anchor,
            )
        for kw in node.keywords:
            if kw.arg != "tag_keys":
                continue
            if not isinstance(kw.value, (ast.Tuple, ast.List)):
                continue
            for element in kw.value.elts:
                if not isinstance(element, ast.Constant):
                    continue
                label = element.value
                if isinstance(label, str) and not self._NAME_RE.match(
                    label
                ):
                    yield (
                        f"label key {label!r} on metric {name!r} "
                        "violates the convention ^[a-z][a-z0-9_]*$",
                        element,
                    )


class UnboundedMetricLabels(Rule):
    """RT010: a metric label whose value is a per-request identity
    (request id, object id, task id, …) mints one Prometheus series
    per id — the head's aggregate table and every scrape grow without
    bound, and no PromQL aggregation wants the id anyway. The memory
    ledger deliberately exports only top-K owners for exactly this
    reason; per-id detail belongs in the state API
    (`ray_tpu state ls objects`), traces, or the flight recorder.
    Also banned: pre-joined src/dst PAIR keys (`flow`, `src_dst`,
    `pair`, `edge`, `route`) — the transfer matrix keys on src_node
    and dst_node as SEPARATE labels (N + N series each, and PromQL
    can aggregate either side); a fused pair label is N² cardinality
    that no aggregation can take apart. Scope: metric declarations
    (`tag_keys=`) and record sites (`.inc/.set/.observe(tags={...})`)
    in the package."""

    id = "RT010"
    title = "unbounded-cardinality metric label (per-request/object id)"
    exclude = ("tests/",)

    _CONSTRUCTORS = ("Counter", "Gauge", "Histogram")
    _RECORDERS = ("inc", "set", "observe")
    #: Label keys whose values are per-entity identities. `job` is
    #: deliberately absent: jobs are few and the ledger/goodput series
    #: key on them by design. `digest`/`shape_digest` are the XLA
    #: compile-watch case: one series per arg-shape set is unbounded
    #: under exactly the recompile storm the series exists to catch —
    #: compile metrics carry the program NAME only, digests stay in
    #: the bounded diagnostic ring (compile_watch.py). src_node /
    #: dst_node are each ALLOWED (node granularity is bounded and the
    #: transfer matrix keys on them by design); what is banned is any
    #: fused src-dst PAIR key — N² series that no PromQL aggregation
    #: can decompose back into per-node sums. `edge` is deliberately
    #: absent: the compiled-DAG channel metrics key on it and a
    #: static DAG's edge set is bounded by the program, not the
    #: cluster — the dynamic pair keys below are what RT010 rejects.
    _BANNED = re.compile(
        r"^(request|object|task|actor|worker|span|trace|lease|"
        r"session|batch|flow|transfer|pull)_?id$|^(oid|tid|rid)$|"
        r"^(shape_)?digest$|^shapes?$|"
        r"^flow$|^(src_dst|dst_src)(_pair)?$|^(node_)?pair$|^route$|"
        r"^(object|obj)_?ref$"
    )

    def _flag(self, key: str, where: str, anchor) -> Iterable[Hit]:
        if isinstance(key, str) and self._BANNED.match(key):
            yield (
                f"metric label {key!r} {where} is a per-entity id — "
                "one exported series per id grows the head table and "
                "every scrape without bound; aggregate (top-K, "
                "totals) or move per-id detail to the state "
                "API/traces",
                anchor,
            )

    def on_call(self, node: ast.Call, ctx: LintContext) -> Iterable[Hit]:
        name = _terminal_name(node.func)
        if name in self._CONSTRUCTORS:
            for kw in node.keywords:
                if kw.arg != "tag_keys" or not isinstance(
                    kw.value, (ast.Tuple, ast.List)
                ):
                    continue
                for element in kw.value.elts:
                    if isinstance(element, ast.Constant):
                        yield from self._flag(
                            element.value,
                            f"declared on {name}()",
                            element,
                        )
        elif name in self._RECORDERS:
            for kw in node.keywords:
                if kw.arg != "tags" or not isinstance(
                    kw.value, ast.Dict
                ):
                    continue
                for key in kw.value.keys:
                    if isinstance(key, ast.Constant):
                        yield from self._flag(
                            key.value, f"passed to .{name}()", key
                        )


ALL_RULES = [
    BlockingGetInActor(),
    PayloadEqualityDedup(),
    WireNondeterminism(),
    ImportTimeForkHazard(),
    UnvalidatedNarrowing(),
    HardcodedNamespace(),
    SwallowedHandlerError(),
    MissingWaitTimeout(),
    MetricNamingConvention(),
    UnboundedMetricLabels(),
]
