"""Runtime lock-order witness: dynamic evidence for RT202/RT203.

The static pass (`devtools/concurrency.py`) reasons about lock order
from source; this module records what a live process ACTUALLY does.
Hot-path locks are created through :func:`make_lock` — when the
witness is enabled (``RT_lock_witness_enabled=1`` in the environment,
or config ``lock_witness_enabled`` via :func:`configure`), the factory
returns an instrumented wrapper that feeds a per-process
:class:`LockWitness`:

* every *first* sighting of "B acquired while A held" records the
  directed edge A→B with the acquiring stack (bounded by
  ``lock_witness_max_edges``; later sightings just count);
* :func:`note_blocking` (hooked in the RPC client) records
  held-while-blocking events — the dynamic RT203;
* the edge graph is cycle-checked on demand (:meth:`LockWitness.
  cycles`), at process exit (stderr warning), and by ``rt.diagnose()``
  — each daemon/worker answers the ``lock_witness`` RPC with its
  snapshot and the doctor folds inversions into ``verdict.locks``.

When the witness is DISABLED, :func:`make_lock` returns a **raw**
``threading.Lock``/``RLock`` — the wrapper is not installed at all, so
the off cost is exactly zero (no runtime branch on the acquire path).
Consequently the switch must be set before the process creates its
locks: flipping the env var on a live process only affects locks
created afterwards.

Lock *names* identify lock roles, not instances: every ``_KeyState``
lock shares one name, so order edges between instances of the same
role merge. Name locks per-instance only when nesting two instances
of the same role is legal (it is not, anywhere in this tree).

Events also land in the flight recorder (kinds ``lock.order``,
``lock.block``, and the pre-existing ``lock.wait``) so ring pulls see
them alongside RPC/task telemetry. The recorder's own ring append is
lock-free, so recording cannot re-enter the witness.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = [
    "make_lock",
    "note_blocking",
    "enabled",
    "install",
    "uninstall",
    "configure",
    "snapshot",
    "witness",
    "LockWitness",
]

_ENV_FLAG = "RT_lock_witness_enabled"
_ENV_MAX_EDGES = "RT_lock_witness_max_edges"

#: The installed witness, or None when disabled. `make_lock` consults
#: this ONCE at lock creation — the off path hands out raw locks.
_WITNESS: Optional["LockWitness"] = None
_env_checked = False
_fork_hook_registered = False


def _truthy(raw: str) -> bool:
    return raw.lower() in ("1", "true", "yes")


class LockWitness:
    """Per-process lock-order graph + held-while-blocking ledger."""

    def __init__(self, max_edges: int = 4096):
        self.max_edges = int(max_edges)
        self._tl = threading.local()
        # Guards the tables below. Deliberately a RAW lock: wrapping
        # it would recurse into on_acquired forever.
        self._mu = threading.Lock()
        #: (held, acquired) -> {"count", "stack"} — stack captured at
        #: first sighting only (format_stack is far too hot otherwise).
        self.edges: Dict[Tuple[str, str], dict] = {}
        self.dropped_edges = 0
        #: (innermost held lock, op) -> {"count", "stack"}.
        self.blocked: Dict[Tuple[str, str], dict] = {}

    # -- hot path --------------------------------------------------------

    def _held(self) -> List[str]:
        held = getattr(self._tl, "held", None)
        if held is None:
            held = self._tl.held = []
        return held

    def _stack(self) -> str:
        # Drop the two witness-internal frames; keep the caller chain.
        return "".join(traceback.format_stack(limit=16)[:-2])

    def on_acquired(self, name: str, wait_s: float) -> None:
        held = self._held()
        new_edges = []
        for other in held:
            if other != name and (other, name) not in self.edges:
                new_edges.append((other, name))
        if new_edges or held:
            with self._mu:
                for key in new_edges:
                    if key in self.edges:
                        continue
                    if len(self.edges) >= self.max_edges:
                        self.dropped_edges += 1
                        continue
                    self.edges[key] = {"count": 0, "stack": self._stack()}
                for other in held:
                    edge = self.edges.get((other, name))
                    if edge is not None:
                        edge["count"] += 1
        held.append(name)
        if new_edges:
            from ray_tpu._private.flight_recorder import record

            for a, b in new_edges:
                record("lock.order", f"{a}->{b}", wait_s * 1e3)
        if wait_s >= 0.001:
            from ray_tpu._private.flight_recorder import record

            record("lock.wait", name, wait_s * 1e3)

    def on_released(self, name: str) -> None:
        held = self._held()
        # Innermost matching entry: RLock re-entry pops symmetrically.
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def note_blocking(self, op: str) -> None:
        held = self._held()
        if not held:
            return
        key = (held[-1], op)
        with self._mu:
            entry = self.blocked.get(key)
            if entry is None:
                self.blocked[key] = {"count": 1, "stack": self._stack()}
            else:
                entry["count"] += 1
                return
        from ray_tpu._private.flight_recorder import record

        record("lock.block", f"{key[0]}|{op}", 0.0)

    # -- cold path -------------------------------------------------------

    def cycles(self) -> List[List[dict]]:
        """Cycles in the recorded order graph, each as a list of edge
        dicts ``{"from", "to", "count", "stack"}`` — both sides of an
        inversion arrive with the stack that created the edge."""
        with self._mu:
            edges = {k: dict(v) for k, v in self.edges.items()}
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        found: List[List[dict]] = []
        seen: set = set()
        for a, b in sorted(edges):
            stack = [(b, [b])]
            while stack:
                node, path = stack.pop()
                if len(path) > 6:
                    continue
                for nxt in sorted(adj.get(node, ())):
                    if nxt == a:
                        order = [a] + path
                        key = frozenset(order)
                        if key in seen:
                            continue
                        seen.add(key)
                        legs = []
                        for i, lock in enumerate(order):
                            nxt_lock = order[(i + 1) % len(order)]
                            edge = edges.get((lock, nxt_lock))
                            if edge is not None:
                                legs.append(
                                    {
                                        "from": lock,
                                        "to": nxt_lock,
                                        "count": edge["count"],
                                        "stack": edge["stack"],
                                    }
                                )
                        found.append(legs)
                    elif nxt not in path:
                        stack.append((nxt, path + [nxt]))
        return found

    def snapshot(self) -> dict:
        """JSON-safe state for the ``lock_witness`` RPC / doctor."""
        with self._mu:
            edges = [
                {"from": a, "to": b, "count": e["count"], "stack": e["stack"]}
                for (a, b), e in self.edges.items()
            ]
            blocked = [
                {"lock": l, "op": op, "count": e["count"], "stack": e["stack"]}
                for (l, op), e in self.blocked.items()
            ]
            dropped = self.dropped_edges
        return {
            "enabled": True,
            "pid": os.getpid(),
            "edges": edges,
            "held_blocking": blocked,
            "dropped_edges": dropped,
            "cycles": self.cycles(),
        }


class _WitnessLock:
    """Instrumented Lock/RLock. Exists only while the witness is
    installed — `make_lock` hands out raw locks otherwise."""

    __slots__ = ("_name", "_inner")

    def __init__(self, name: str, kind: str = "lock"):
        self._name = name
        self._inner = (
            threading.RLock() if kind == "rlock" else threading.Lock()
        )

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.monotonic()
        ok = self._inner.acquire(blocking, timeout)
        witness_ = _WITNESS
        if ok and witness_ is not None:
            witness_.on_acquired(self._name, time.monotonic() - t0)
        return ok

    def release(self) -> None:
        witness_ = _WITNESS
        if witness_ is not None:
            witness_.on_released(self._name)
        self._inner.release()

    def locked(self) -> bool:
        locked_fn = getattr(self._inner, "locked", None)
        return locked_fn() if locked_fn is not None else False

    def __enter__(self) -> "_WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _reset_after_fork() -> None:
    # The child inherits the parent's tables and OTHER threads' held
    # stacks — both are garbage post-fork. Start a fresh witness with
    # the same bound (the wrapped locks keep working: they read the
    # module global on every acquire).
    global _WITNESS
    if _WITNESS is not None:
        _WITNESS = LockWitness(max_edges=_WITNESS.max_edges)


def _exit_report() -> None:
    witness_ = _WITNESS
    if witness_ is None:
        return
    cycles = witness_.cycles()
    if not cycles:
        return
    import sys

    print(
        f"[lock-witness] pid {os.getpid()}: "
        f"{len(cycles)} lock-order inversion(s) observed at exit:",
        file=sys.stderr,
    )
    for legs in cycles:
        for leg in legs:
            print(
                f"  {leg['from']} -> {leg['to']} "
                f"(seen {leg['count']}x)\n{leg['stack']}",
                file=sys.stderr,
            )


def install(max_edges: Optional[int] = None) -> LockWitness:
    """Install the process witness (idempotent). Called automatically
    when the env flag is set; call directly in tests/benches."""
    global _WITNESS, _fork_hook_registered
    if _WITNESS is None:
        if max_edges is None:
            max_edges = int(os.environ.get(_ENV_MAX_EDGES, "4096"))
        _WITNESS = LockWitness(max_edges=max_edges)
        if not _fork_hook_registered:
            _fork_hook_registered = True
            os.register_at_fork(after_in_child=_reset_after_fork)
            import atexit

            atexit.register(_exit_report)
    return _WITNESS


def uninstall() -> None:
    """Drop the witness (tests/benches). Already-wrapped locks keep
    working but stop recording."""
    global _WITNESS
    _WITNESS = None


def _maybe_install_from_env() -> None:
    global _env_checked
    if _env_checked:
        return
    _env_checked = True
    if _truthy(os.environ.get(_ENV_FLAG, "")):
        install()


def configure(config) -> None:
    """Apply cluster config (same contract as flight_recorder: the
    env var wins over the cluster flag, so one process can opt out)."""
    env = os.environ.get(_ENV_FLAG)
    if env is not None:
        if _truthy(env):
            install(max_edges=getattr(config, "lock_witness_max_edges", None))
        return
    if getattr(config, "lock_witness_enabled", False):
        install(max_edges=getattr(config, "lock_witness_max_edges", None))


def enabled() -> bool:
    _maybe_install_from_env()
    return _WITNESS is not None


def witness() -> Optional[LockWitness]:
    return _WITNESS


def make_lock(name: str, kind: str = "lock"):
    """The hot-path lock factory. Witness off → a RAW threading lock
    (zero overhead, no wrapper); witness on → an instrumented one."""
    if not enabled():
        return threading.RLock() if kind == "rlock" else threading.Lock()
    return _WitnessLock(name, kind)


def note_blocking(op: str) -> None:
    """Record 'about to block while holding a witness lock' (hooked on
    the RPC client call path). One global read when disabled."""
    witness_ = _WITNESS
    if witness_ is not None:
        witness_.note_blocking(op)


def snapshot() -> dict:
    """This process's witness state ({"enabled": False} when off)."""
    witness_ = _WITNESS
    if witness_ is None:
        return {"enabled": False, "pid": os.getpid()}
    return witness_.snapshot()
