"""Accelerator hot-path analyzer (`ray_tpu devtools accel`,
rules RT301-RT306) — the static twin of `_private/compile_watch.py`.

Fourth devtools layer (after lint's per-file idioms, check's
cross-process contracts, and race's thread/lock model): the failure
modes that silently break "runs as fast as the hardware allows" are
XLA-layer — recompile storms, hidden device->host syncs, donation
mistakes — and PR 15's compile watch only convicts them *at runtime,
after the step time is already lost*.  This pass rejects the same
bugs at `devtools all` time.

Two phases over the whole tree:

**Phase 1 — inventory.**  Every wrapping site (`jax.jit(...)`,
``partial(jax.jit, ...)(impl)``, ``@jax.jit`` / ``@partial(jax.jit,
...)`` decorators, ``checked_shard_map``) with its resolved
``donate_argnums`` (including the ``accel_donate(...)`` gate),
``static_argnums``/``static_argnames``, how the wrapper is bound
(module global, ``self`` attribute, local, decorated def, immediately
invoked), whether it flows into ``compile_watch.instrument`` (and
under what program name — f-string names become ``fnmatch`` patterns,
``mpmd.s{i}.{k}`` -> ``mpmd.s*.*``), plus the *hot contexts*: functions
billed by ``step_telemetry.phase_timer``, ``@rt.remote`` actor
methods, and any function whose loop dispatches a known-jitted
callable.  Module-level forwarders (a function whose return is a
1:1 positional call of a jit binding — the ``decode_step`` ->
``_decode_step_jit`` idiom in models/generate.py) inherit the inner
wrapper's donate/static signature, so call sites in *other* modules
are judged too.

**Phase 2 — judgment.**

| id    | judgment                                                     |
|-------|--------------------------------------------------------------|
| RT301 | jit/donate wrapper constructed inside a loop, or in a       |
|       | per-call function body — re-traces and re-compiles every    |
|       | call, defeating the compile cache.  One-time contexts       |
|       | (init/build/make/setup/warm/test/main names, factories      |
|       | that return the wrapper, the lazy module-global cache       |
|       | idiom) are exempt.                                          |
| RT302 | recompile-hazard argument: ``len(...)`` (or an unhashable   |
|       | list/dict/set literal) reaching a static position — every   |
|       | distinct value compiles a new program — or a               |
|       | ``len()``-bounded slice reaching a traced position (shape   |
|       | drift per batch); also per-call-computed static_argnums at  |
|       | the wrap site.  The static cause behind `verdict.compile`   |
|       | shape-drift storms.                                         |
| RT303 | hidden host sync in a hot loop: ``float()``/``int()``/      |
|       | ``bool``-branch/``.item()``/``np.asarray``/``print`` applied|
|       | to a device value inside a loop of a hot context.  Each one |
|       | blocks dispatch for a device round-trip — the class whose   |
|       | removal bought PR 12 ~10% tokens/s.                         |
| RT304 | use-after-donate: a plain name passed at a donated argnum   |
|       | and read again before rebinding — XLA consumed the buffer.  |
| RT305 | timing code measures a dispatched-but-unblocked device      |
|       | computation: clock read, jitted call, clock subtraction     |
|       | with no ``block_until_ready`` (or host materialization)     |
|       | in between — the benchmark reports dispatch, not compute.   |
| RT306 | jitted program invisible to the compile watch: the wrapper  |
|       | never flows through ``compile_watch.instrument``, so a      |
|       | recompile storm attributes to ``(unregistered)`` and the    |
|       | doctor cannot name the program.                             |
| RT390 | stale or unknown ``# rt: noqa[RT3xx]`` suppression (the     |
|       | shared hygiene contract; see lint.noqa_hygiene).            |

Scoping: RT303/RT305/RT306 stay out of test files (``test_*.py``,
``tests/``, ``conftest.py``) — tests time, sync and jit deliberately;
RT301/RT302/RT304 apply everywhere.  Precision over recall
throughout: aliased wrappers, cross-variable taint through containers
and dynamically-chosen callees stay silent rather than guessing —
the runtime twin (`compile_watch`, `rt.diagnose()`'s
`verdict.compile`) supplies the dynamic evidence this pass cannot
see, and `build_inventory()` is the bridge back: the doctor resolves
a live storm's program name against this pass's inventory so the
runtime conviction points at the static fix.

Shares the lint/check/race contract: ``# rt: noqa[RT3xx]``
suppressions, ``--json``, exit 0 clean / 1 findings / 2 usage errors.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .lint import (
    Finding,
    _dotted,
    _is_remote_decorator,
    _iter_py_files,
    _parse_noqa,
    noqa_hygiene,
)

__all__ = [
    "accel_sources",
    "accel_paths",
    "build_inventory",
    "build_inventory_sources",
    "main",
    "RULES",
]

#: id -> one-line title (the --list-rules table).
RULES: Dict[str, str] = {
    "RT301": "jit/donate wrapper constructed per call (loop or call-path body)",
    "RT302": "recompile-hazard argument reaches a static/traced position",
    "RT303": "hidden host sync on a device value in a hot loop",
    "RT304": "buffer read after being donated to a jitted call",
    "RT305": "timing measures a dispatched-but-unblocked device computation",
    "RT306": "jitted program not registered with compile_watch.instrument",
    "RT390": "stale or unknown '# rt: noqa' suppression (accel family)",
}

_JIT_NAMES = {"jax.jit", "jit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}
_SHARD_NAMES = {"checked_shard_map", "shard_map"}
_TIME_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "monotonic", "perf_counter",
}
#: Host materializers: calling one *blocks* on the device value (so it
#: also discharges a pending RT305 dispatch).
_SYNC_CALLS = {"float", "int", "np.asarray", "numpy.asarray",
               "jax.device_get", "device_get"}
_ONETIME_PREFIXES = ("init", "build", "make", "setup", "warm", "test",
                     "main", "create", "bench")


def _is_test_path(path: str) -> bool:
    norm = path.replace(os.sep, "/")
    base = os.path.basename(norm)
    return (
        base.startswith("test_")
        or base == "conftest.py"
        or "/tests/" in norm
        or norm.startswith("tests/")
    )


def _const_ints(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """(1, 2) / [1] / 3 -> ints; accel_donate(1, 2) -> (1, 2); else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func)
        if dotted == "accel_donate" or dotted.endswith(".accel_donate"):
            out = []
            for arg in node.args:
                if isinstance(arg, ast.Constant) and isinstance(arg.value, int):
                    out.append(arg.value)
                else:
                    return None
            return tuple(out)
    return None


def _const_strs(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _contains_len(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _dotted(sub.func) == "len":
            return True
    return False


def _program_name(node: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """instrument() first arg -> (name, "literal"|"pattern")."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, "literal"
    if isinstance(node, ast.JoinedStr):
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:
                parts.append("*")
        return "".join(parts), "pattern"
    return None, None


@dataclass
class _Wrap:
    """One jit / shard_map wrapping site (phase-1 inventory row)."""

    path: str
    line: int
    col: int
    kind: str  # "jit" | "shard_map"
    target: str  # dotted name of the wrapped callable ("" if opaque)
    binding: Optional[Tuple[str, str]]  # ("global"|"self"|"local"|"def", name)
    donate: Tuple[int, ...] = ()
    static_nums: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()
    fresh_static: bool = False  # static argnums/names computed per call
    enclosing: Optional[str] = None  # qualname of enclosing function
    in_loop: bool = False
    immediately_called: bool = False
    returned: bool = False  # factory idiom: `return jax.jit(...)`
    registered: bool = False
    program: Optional[str] = None
    program_kind: Optional[str] = None  # "literal" | "pattern"
    hazards: List[dict] = field(default_factory=list)  # RT302, for doctor


@dataclass
class _FnRec:
    """One function body to judge in phase 2."""

    path: str
    qualname: str
    node: ast.AST
    class_name: Optional[str] = None
    is_remote_method: bool = False
    uses_phase_timer: bool = False


@dataclass
class _ModuleScan:
    path: str
    source: str
    tree: ast.Module
    wraps: List[_Wrap] = field(default_factory=list)
    #: binding key -> (program, kind) from instrument(name, <binding>).
    regs: Dict[Tuple[str, str], Tuple[Optional[str], Optional[str]]] = field(
        default_factory=dict
    )
    #: bindings assigned a compile_watch.instrument(...) result — a
    #: WatchedFunction IS a jitted-program handle, so calls through it
    #: participate in taint/dispatch tracking (donate/static unknown).
    watched: List[Tuple[str, str]] = field(default_factory=list)
    funcs: List[_FnRec] = field(default_factory=list)


@dataclass
class _Callee:
    """Resolved signature of a jitted callable, for call-site rules."""

    donate: Tuple[int, ...]
    static_nums: Tuple[int, ...]
    static_names: Tuple[str, ...]
    wrap: Optional[_Wrap]  # None once terminal-name resolution is ambiguous


def _merge_callee(into: Dict[str, _Callee], name: str, cal: _Callee) -> None:
    """Terminal-name registry: collisions keep jittedness but drop the
    donate/static signature — wrong donation info is worse than none."""
    prev = into.get(name)
    if prev is None:
        into[name] = cal
    elif prev.wrap is not cal.wrap:
        into[name] = _Callee((), (), (), None)


class _Scanner(ast.NodeVisitor):
    """Phase 1: one walk per module collecting wraps, registrations and
    judgeable function bodies."""

    def __init__(self, mod: _ModuleScan):
        self.mod = mod
        self.func_stack: List[Tuple[str, ast.AST, Set[str]]] = []
        self.class_stack: List[Tuple[str, bool]] = []
        self.loop_depth = 0
        self._consumed: Set[int] = set()

    # -- wrap recognition ---------------------------------------------
    def _jit_wrap_of(self, node: ast.AST) -> Optional[Tuple[ast.Call, List[ast.keyword], Optional[ast.expr]]]:
        """node is a jit wrapping call -> (call, keywords, target expr)."""
        if not isinstance(node, ast.Call):
            return None
        dotted = _dotted(node.func)
        if dotted in _JIT_NAMES:
            target = node.args[0] if node.args else None
            return node, list(node.keywords), target
        # partial(jax.jit, **kw)(impl): the outer application.
        if isinstance(node.func, ast.Call):
            inner = node.func
            if (
                _dotted(inner.func) in _PARTIAL_NAMES
                and inner.args
                and _dotted(inner.args[0]) in _JIT_NAMES
            ):
                target = node.args[0] if node.args else None
                return node, list(inner.keywords), target
        return None

    def _make_wrap(
        self,
        node: ast.Call,
        keywords: Sequence[ast.keyword],
        target: Optional[ast.expr],
        kind: str = "jit",
        binding: Optional[Tuple[str, str]] = None,
        immediately_called: bool = False,
        returned: bool = False,
    ) -> _Wrap:
        donate: Tuple[int, ...] = ()
        static_nums: Tuple[int, ...] = ()
        static_names: Tuple[str, ...] = ()
        fresh = False
        for kw in keywords:
            if kw.arg == "donate_argnums":
                donate = _const_ints(kw.value) or ()
            elif kw.arg == "static_argnums":
                vals = _const_ints(kw.value)
                if vals is None and isinstance(
                    kw.value, (ast.Call, ast.ListComp, ast.GeneratorExp)
                ):
                    fresh = True
                static_nums = vals or ()
            elif kw.arg == "static_argnames":
                vals = _const_strs(kw.value)
                if vals is None and isinstance(
                    kw.value, (ast.Call, ast.ListComp, ast.GeneratorExp)
                ):
                    fresh = True
                static_names = vals or ()
        wrap = _Wrap(
            path=self.mod.path,
            line=node.lineno,
            col=node.col_offset + 1,
            kind=kind,
            target=_dotted(target) if target is not None else "",
            binding=binding,
            donate=donate,
            static_nums=static_nums,
            static_names=static_names,
            fresh_static=fresh,
            enclosing=self.func_stack[-1][0] if self.func_stack else None,
            in_loop=self.loop_depth > 0,
            immediately_called=immediately_called,
            returned=returned,
        )
        self.mod.wraps.append(wrap)
        self._consumed.add(id(node))
        return wrap

    def _binding_for(self, tgt: ast.expr) -> Optional[Tuple[str, str]]:
        if isinstance(tgt, ast.Name):
            if not self.func_stack:
                return ("global", tgt.id)
            if tgt.id in self.func_stack[-1][2]:  # `global X` declared
                return ("global", tgt.id)
            return ("local", tgt.id)
        if (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
        ):
            return ("self", tgt.attr)
        return None

    # -- visits --------------------------------------------------------
    def visit_Global(self, node: ast.Global) -> None:
        if self.func_stack:
            self.func_stack[-1][2].update(node.names)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        is_actor = any(_is_remote_decorator(d) for d in node.decorator_list)
        self.class_stack.append((node.name, is_actor))
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node) -> None:
        qual = ".".join(
            [c for c, _ in self.class_stack]
            + [f[0].rsplit(".", 1)[-1] for f in self.func_stack]
            + [node.name]
        )
        uses_timer = any(
            isinstance(sub, ast.Call)
            and _dotted(sub.func).endswith("phase_timer")
            for sub in ast.walk(node)
        )
        in_actor = bool(self.class_stack) and self.class_stack[-1][1]
        decorated_remote = any(
            _is_remote_decorator(d) for d in node.decorator_list
        )
        self.mod.funcs.append(
            _FnRec(
                path=self.mod.path,
                qualname=qual,
                node=node,
                class_name=self.class_stack[-1][0] if self.class_stack else None,
                is_remote_method=in_actor or decorated_remote,
                uses_phase_timer=uses_timer,
            )
        )
        # Decorator wraps: @jax.jit / @partial(jax.jit, ...).
        for dec in node.decorator_list:
            if _dotted(dec) in _JIT_NAMES:
                fake = ast.Call(func=dec, args=[], keywords=[])
                ast.copy_location(fake, dec)
                self._make_wrap(fake, [], None, binding=("def", node.name))
                self.mod.wraps[-1].target = qual
            elif isinstance(dec, ast.Call):
                got = self._jit_wrap_of_decorator(dec)
                if got is not None:
                    self._make_wrap(dec, got, None, binding=("def", node.name))
                    self.mod.wraps[-1].target = qual
        outer_loop, self.loop_depth = self.loop_depth, 0
        self.func_stack.append((qual, node, set()))
        self.generic_visit(node)
        self.func_stack.pop()
        self.loop_depth = outer_loop

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _jit_wrap_of_decorator(self, dec: ast.Call) -> Optional[List[ast.keyword]]:
        if _dotted(dec.func) in _JIT_NAMES:
            return list(dec.keywords)
        if (
            _dotted(dec.func) in _PARTIAL_NAMES
            and dec.args
            and _dotted(dec.args[0]) in _JIT_NAMES
        ):
            return list(dec.keywords)
        return None

    def _visit_loop(self, node) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def visit_Assign(self, node: ast.Assign) -> None:
        got = self._wrap_like(node.value)
        binding = (
            self._binding_for(node.targets[0])
            if len(node.targets) == 1
            else None
        )
        if got is not None:
            call, kws, target, kind, registered, prog, pk = got
            wrap = self._make_wrap(call, kws, target, kind=kind, binding=binding)
            if registered:
                wrap.registered, wrap.program, wrap.program_kind = True, prog, pk
        elif (
            binding is not None
            and isinstance(node.value, ast.Call)
            and self._is_instrument(node.value)
        ):
            self.mod.watched.append(binding)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            got = self._wrap_like(node.value)
            if got is not None:
                call, kws, target, kind, registered, prog, pk = got
                wrap = self._make_wrap(call, kws, target, kind=kind, returned=True)
                if registered:
                    wrap.registered, wrap.program, wrap.program_kind = True, prog, pk
        self.generic_visit(node)

    def _wrap_like(self, value: ast.expr):
        """value is a wrap, an instrument(<wrap>), or instrument-applied
        wrap -> (call, keywords, target, kind, registered, prog, prog_kind)."""
        got = self._jit_wrap_of(value)
        if got is not None:
            call, kws, target = got
            return call, kws, target, "jit", False, None, None
        if isinstance(value, ast.Call):
            dotted = _dotted(value.func)
            if dotted.rsplit(".", 1)[-1] in _SHARD_NAMES:
                target = value.args[0] if value.args else None
                return value, list(value.keywords), target, "shard_map", False, None, None
            if self._is_instrument(value) and len(value.args) >= 2:
                inner = self._jit_wrap_of(value.args[1])
                if inner is not None:
                    prog, pk = _program_name(value.args[0])
                    call, kws, target = inner
                    return call, kws, target, "jit", True, prog, pk
        return None

    def _is_instrument(self, call: ast.Call) -> bool:
        dotted = _dotted(call.func)
        return dotted == "instrument" or dotted.endswith(".instrument")

    def visit_Call(self, node: ast.Call) -> None:
        # instrument(name, X): register X whether X is a wrap, a name,
        # or a self attribute.
        if self._is_instrument(node) and len(node.args) >= 2:
            prog, pk = _program_name(node.args[0])
            inner = self._jit_wrap_of(node.args[1])
            if inner is not None:
                if id(node.args[1]) not in self._consumed:
                    call, kws, target = inner
                    wrap = self._make_wrap(call, kws, target)
                    wrap.registered, wrap.program, wrap.program_kind = True, prog, pk
            else:
                key = self._binding_for(node.args[1])
                if key is not None:
                    # A local name registers its local binding; fall back
                    # to the module-global spelling too (lazy-init idiom).
                    self.mod.regs[key] = (prog, pk)
                    if key[0] == "local":
                        self.mod.regs[("global", key[1])] = (prog, pk)
        # Immediately-invoked wrap: jax.jit(f, ...)(args).
        got = self._jit_wrap_of(node.func) if isinstance(node.func, ast.Call) else None
        if got is not None and _dotted(node.func.func) not in _PARTIAL_NAMES:
            if id(node.func) not in self._consumed:
                call, kws, target = got
                self._make_wrap(call, kws, target, immediately_called=True)
        # Anonymous wrap used as a plain expression/argument.
        if id(node) not in self._consumed and self._jit_wrap_of(node) is not None:
            call, kws, target = self._jit_wrap_of(node)
            self._make_wrap(call, kws, target)
        self.generic_visit(node)


def _scan_module(path: str, source: str, tree: ast.Module) -> _ModuleScan:
    mod = _ModuleScan(path=path, source=source, tree=tree)
    _Scanner(mod).visit(tree)
    # Resolve name-flow registrations: instrument("name", binding).
    for wrap in mod.wraps:
        if wrap.registered or wrap.binding is None:
            continue
        reg = mod.regs.get(wrap.binding)
        if reg is None and wrap.binding[0] == "def":
            reg = mod.regs.get(("global", wrap.binding[1]))
        if reg is not None:
            wrap.registered = True
            wrap.program, wrap.program_kind = reg
    return mod


def _forwarders(mod: _ModuleScan) -> Dict[str, _Wrap]:
    """Module-level `def f(...): return <jit binding>(...)` forwarders.
    1:1 positional forwarding inherits the wrapper's donate/static."""
    by_global: Dict[str, _Wrap] = {}
    for wrap in mod.wraps:
        if wrap.kind == "jit" and wrap.binding and wrap.binding[0] == "global":
            by_global[wrap.binding[1]] = wrap
    out: Dict[str, _Wrap] = {}
    if not by_global:
        return out
    for node in mod.tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for stmt in ast.walk(node):
            if not (isinstance(stmt, ast.Return) and isinstance(stmt.value, ast.Call)):
                continue
            callee = stmt.value.func
            if isinstance(callee, ast.Name) and callee.id in by_global:
                inner = by_global[callee.id]
                params = [a.arg for a in node.args.args]
                call_args = [
                    a.id if isinstance(a, ast.Name) else None
                    for a in stmt.value.args
                ]
                if call_args and call_args == params[: len(call_args)]:
                    out[node.name] = inner  # positional 1:1 — inherit
                else:
                    out.setdefault(
                        node.name, _Wrap(inner.path, inner.line, inner.col,
                                         "jit", inner.target, None)
                    )
                break
    return out


# ---------------------------------------------------------------------------
# phase 2: per-function judgment
# ---------------------------------------------------------------------------


class _FuncJudge:
    """Linear, source-order walk of one function body tracking device
    taint, pending dispatch, live donations and clock reads."""

    def __init__(
        self,
        rec: _FnRec,
        callees: Dict[str, _Callee],
        self_callees: Dict[str, _Callee],
        local_callees: Dict[str, _Callee],
        findings: List[Finding],
        in_test_file: bool,
    ):
        self.rec = rec
        self.callees = callees
        self.self_callees = self_callees
        self.local_callees = local_callees
        self.findings = findings
        self.in_test_file = in_test_file
        self.tainted: Set[str] = set()
        self.time_vars: Set[str] = set()
        self.pending_dispatch = False
        self.pending_line = 0
        self.donated: Dict[str, Tuple[int, str]] = {}  # name -> (line, callee)
        self.loop_depth = 0
        self.hot_reason: Optional[str] = None
        if rec.uses_phase_timer:
            self.hot_reason = "billed by step_telemetry.phase_timer"
        elif rec.is_remote_method:
            self.hot_reason = "@rt.remote dispatch path"

    # -- entry ---------------------------------------------------------
    def run(self) -> None:
        body = getattr(self.rec.node, "body", [])
        if self.hot_reason is None and self._has_jit_loop(body):
            self.hot_reason = "loop dispatches a jitted program"
        for stmt in body:
            self._stmt(stmt)

    def _has_jit_loop(self, body) -> bool:
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.For, ast.AsyncFor, ast.While)):
                    for inner in ast.walk(sub):
                        if isinstance(inner, ast.Call) and self._callee(inner) is not None:
                            return True
        return False

    def _callee(self, call: ast.Call) -> Optional[_Callee]:
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in self.local_callees:
                return self.local_callees[func.id]
            return self.callees.get(func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                return self.self_callees.get(func.attr)
            return self.callees.get(func.attr)
        return None

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(
                path=self.rec.path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0) + 1,
                rule=rule,
                message=message,
            )
        )

    # -- statements ----------------------------------------------------
    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # judged as their own records
        if isinstance(stmt, ast.Assign):
            taint = self._expr(stmt.value)
            is_time = (
                isinstance(stmt.value, ast.Call)
                and _dotted(stmt.value.func) in _TIME_CALLS
            )
            for tgt in stmt.targets:
                self._store(tgt, taint, is_time)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            taint = self._expr(stmt.value)
            is_time = (
                isinstance(stmt.value, ast.Call)
                and _dotted(stmt.value.func) in _TIME_CALLS
            )
            self._store(stmt.target, taint, is_time)
        elif isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value)
            if isinstance(stmt.target, ast.Name):
                self._load_name(stmt.target)
        elif isinstance(stmt, ast.Expr):
            self._expr(stmt.value)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr(stmt.value)
        elif isinstance(stmt, (ast.If,)):
            self._branch_test(stmt.test)
            for s in stmt.body:
                self._stmt(s)
            for s in stmt.orelse:
                self._stmt(s)
        elif isinstance(stmt, (ast.While,)):
            self.loop_depth += 1
            self._branch_test(stmt.test)
            for s in stmt.body:
                self._stmt(s)
            self.loop_depth -= 1
            for s in stmt.orelse:
                self._stmt(s)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter)
            self.loop_depth += 1
            for s in stmt.body:
                self._stmt(s)
            self.loop_depth -= 1
            for s in stmt.orelse:
                self._stmt(s)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr)
            for s in stmt.body:
                self._stmt(s)
        elif isinstance(stmt, ast.Try):
            for s in stmt.body:
                self._stmt(s)
            for handler in stmt.handlers:
                for s in handler.body:
                    self._stmt(s)
            for s in stmt.orelse:
                self._stmt(s)
            for s in stmt.finalbody:
                self._stmt(s)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child)

    def _store(self, tgt: ast.expr, taint: bool, is_time: bool) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._store(elt, taint, False)
            return
        if isinstance(tgt, ast.Starred):
            self._store(tgt.value, taint, False)
            return
        if isinstance(tgt, ast.Name):
            self.donated.pop(tgt.id, None)  # rebound — donation consumed
            self.time_vars.discard(tgt.id)
            if is_time:
                self.time_vars.add(tgt.id)
            if taint:
                self.tainted.add(tgt.id)
            else:
                self.tainted.discard(tgt.id)
        elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
            self._expr(tgt.value)

    def _branch_test(self, test: ast.expr) -> None:
        taint = self._expr(test)
        if taint and self._hot_now():
            self._emit(
                "RT303",
                test,
                f"{self.rec.qualname} branches on a device value inside a "
                f"hot loop ({self.hot_reason}) — the bool() forces a "
                f"device->host sync every iteration; compute the predicate "
                f"on host or hoist it out of the loop",
            )
            self.pending_dispatch = False

    def _hot_now(self) -> bool:
        return (
            self.loop_depth > 0
            and self.hot_reason is not None
            and not self.in_test_file
        )

    # -- expressions ---------------------------------------------------
    def _load_name(self, node: ast.Name) -> bool:
        if node.id in self.donated:
            line, callee = self.donated.pop(node.id)
            self._emit(
                "RT304",
                node,
                f"{self.rec.qualname} reads '{node.id}' after donating it "
                f"to {callee} (line {line}) — XLA consumed the buffer; "
                f"rebind the result or drop it from donate_argnums",
            )
        return node.id in self.tainted

    def _expr(self, node: Optional[ast.expr]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return self._load_name(node)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.BinOp):
            return self._binop(node)
        if isinstance(node, ast.BoolOp):
            return any(self._expr(v) for v in list(node.values))
        if isinstance(node, ast.UnaryOp):
            return self._expr(node.operand)
        if isinstance(node, ast.Compare):
            got = self._expr(node.left)
            for cmp in node.comparators:
                got = self._expr(cmp) or got
            return got
        if isinstance(node, ast.Subscript):
            got = self._expr(node.value)
            self._expr(node.slice) if isinstance(node.slice, ast.expr) else None
            return got
        if isinstance(node, ast.Attribute):
            return self._expr(node.value)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._expr(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            got = False
            for k, v in zip(node.keys, node.values):
                got = self._expr(k) or got if k is not None else got
                got = self._expr(v) or got
            return got
        if isinstance(node, ast.IfExp):
            self._expr(node.test)
            return self._expr(node.body) or self._expr(node.orelse)
        if isinstance(node, ast.Starred):
            return self._expr(node.value)
        if isinstance(node, ast.JoinedStr):
            return any(
                self._expr(v.value)
                for v in node.values
                if isinstance(v, ast.FormattedValue)
            )
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            # Separate scope — no taint judgments inside, but a sync
            # call in the element expression still blocks on the device
            # (the `{k: np.asarray(v) ...}` materialize idiom), so it
            # discharges a pending RT305 dispatch.
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    dotted = _dotted(sub.func)
                    if dotted in _SYNC_CALLS or dotted in ("float", "int") or (
                        dotted.rsplit(".", 1)[-1] in ("item", "block_until_ready")
                    ):
                        self.pending_dispatch = False
                        break
            return False
        if isinstance(node, ast.Lambda):
            return False
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
        return False

    def _binop(self, node: ast.BinOp) -> bool:
        left_is_clock = (
            isinstance(node.left, ast.Call)
            and _dotted(node.left.func) in _TIME_CALLS
        ) or (
            isinstance(node.left, ast.Name) and node.left.id in self.time_vars
        )
        right_is_timevar = (
            isinstance(node.right, ast.Name) and node.right.id in self.time_vars
        )
        if isinstance(node.op, ast.Sub) and right_is_timevar and left_is_clock:
            if self.pending_dispatch and not self.in_test_file:
                self._emit(
                    "RT305",
                    node,
                    f"{self.rec.qualname} reads the clock after a jitted "
                    f"call (line {self.pending_line}) with no "
                    f"block_until_ready in between — the elapsed time "
                    f"measures async dispatch, not device compute",
                )
            self.pending_dispatch = False
            return False
        got = self._expr(node.left)
        return self._expr(node.right) or got

    def _call(self, node: ast.Call) -> bool:
        dotted = _dotted(node.func)
        terminal = dotted.rsplit(".", 1)[-1] if dotted else ""
        # block_until_ready discharges a pending dispatch.
        if terminal == "block_until_ready":
            for arg in node.args:
                self._expr(arg)
            if isinstance(node.func, ast.Attribute):
                self._expr(node.func.value)
            self.pending_dispatch = False
            return True  # still a device value (jax returns it)
        callee = self._callee(node)
        if callee is not None:
            self._jitted_call(node, callee)
            return True
        # Host syncs.
        if dotted in ("float", "int") and len(node.args) == 1:
            taint = self._expr(node.args[0])
            if taint:
                if self._hot_now():
                    self._emit(
                        "RT303",
                        node,
                        f"{self.rec.qualname} calls {dotted}() on a device "
                        f"value inside a hot loop ({self.hot_reason}) — "
                        f"each call blocks for a device->host round-trip; "
                        f"batch the transfer outside the loop",
                    )
                self.pending_dispatch = False
            return False
        if dotted in _SYNC_CALLS:
            taint = any(self._expr(a) for a in list(node.args))
            if taint:
                if self._hot_now():
                    self._emit(
                        "RT303",
                        node,
                        f"{self.rec.qualname} materializes a device value "
                        f"via {dotted}() inside a hot loop "
                        f"({self.hot_reason}) — each call is a blocking "
                        f"device->host transfer",
                    )
                self.pending_dispatch = False
            return False
        if terminal == "item" and isinstance(node.func, ast.Attribute):
            taint = self._expr(node.func.value)
            if taint:
                if self._hot_now():
                    self._emit(
                        "RT303",
                        node,
                        f"{self.rec.qualname} calls .item() on a device "
                        f"value inside a hot loop ({self.hot_reason}) — "
                        f"blocking device->host sync per iteration",
                    )
                self.pending_dispatch = False
            return False
        if dotted == "print":
            taint = any(self._expr(a) for a in list(node.args))
            if taint:
                if self._hot_now():
                    self._emit(
                        "RT303",
                        node,
                        f"{self.rec.qualname} prints a device value inside "
                        f"a hot loop ({self.hot_reason}) — formatting "
                        f"forces a device->host sync; log a host copy "
                        f"outside the loop",
                    )
                self.pending_dispatch = False
            return False
        # Opaque call: evaluate operands, propagate taint through.
        got = False
        for arg in node.args:
            got = self._expr(arg) or got
        for kw in node.keywords:
            got = self._expr(kw.value) or got
        if isinstance(node.func, ast.Attribute):
            got = self._expr(node.func.value) or got
        return got

    def _jitted_call(self, node: ast.Call, callee: _Callee) -> None:
        # RT302: hazard arguments.
        for idx, arg in enumerate(node.args):
            is_static = idx in callee.static_nums
            if is_static:
                if _contains_len(arg):
                    self._hazard(
                        node, callee,
                        f"static argument {idx} derives from len(...) — "
                        f"every distinct length traces and compiles a new "
                        f"program (recompile storm under varying batch)",
                    )
                elif isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    self._hazard(
                        node, callee,
                        f"static argument {idx} is an unhashable "
                        f"{type(arg).__name__.lower()} literal — jit "
                        f"cannot cache on it",
                    )
            else:
                self._traced_shape_hazard(node, callee, arg)
            self._expr(arg)
        for kw in node.keywords:
            if kw.arg is not None and kw.arg in callee.static_names:
                if _contains_len(kw.value):
                    self._hazard(
                        node, callee,
                        f"static argument '{kw.arg}' derives from len(...) "
                        f"— every distinct length compiles a new program",
                    )
                elif isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                    self._hazard(
                        node, callee,
                        f"static argument '{kw.arg}' is an unhashable "
                        f"{type(kw.value).__name__.lower()} literal",
                    )
            else:
                self._traced_shape_hazard(node, callee, kw.value)
            self._expr(kw.value)
        # RT304: donations of plain names.
        for idx in callee.donate:
            if idx < len(node.args) and isinstance(node.args[idx], ast.Name):
                name = node.args[idx].id
                label = callee.wrap.target or "a jitted program" if callee.wrap else "a jitted program"
                self.donated[name] = (node.lineno, label)
        self.pending_dispatch = True
        self.pending_line = node.lineno

    def _traced_shape_hazard(self, node: ast.Call, callee: _Callee, arg: ast.expr) -> None:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Subscript) and isinstance(sub.slice, ast.Slice):
                bounds = [sub.slice.lower, sub.slice.upper]
                if any(b is not None and _contains_len(b) for b in bounds):
                    self._hazard(
                        node, callee,
                        "a len()-bounded slice reaches a traced position — "
                        "the operand shape drifts per batch; pad to a "
                        "fixed bucket instead",
                    )
                    return

    def _hazard(self, node: ast.Call, callee: _Callee, detail: str) -> None:
        message = f"{self.rec.qualname}: {detail}"
        self._emit("RT302", node, message)
        if callee.wrap is not None:
            callee.wrap.hazards.append(
                {
                    "rule": "RT302",
                    "path": self.rec.path,
                    "line": node.lineno,
                    "message": message,
                }
            )


# ---------------------------------------------------------------------------
# whole-program drivers
# ---------------------------------------------------------------------------


def _scan_all(sources: Sequence[Tuple[str, str]]):
    mods: List[_ModuleScan] = []
    parse_errors: List[Finding] = []
    for path, source in sources:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            parse_errors.append(
                Finding(
                    path=path,
                    line=e.lineno or 1,
                    col=(e.offset or 0) + 1,
                    rule="RT000",
                    message=f"file does not parse: {e.msg}",
                )
            )
            continue
        mods.append(_scan_module(path, source, tree))
    return mods, parse_errors


def _callee_registry(mods: Sequence[_ModuleScan]):
    """Cross-module terminal-name registry of jitted callables."""
    callees: Dict[str, _Callee] = {}
    self_callees: Dict[str, _Callee] = {}
    for mod in mods:
        for wrap in mod.wraps:
            if wrap.kind != "jit":
                continue
            cal = _Callee(wrap.donate, wrap.static_nums, wrap.static_names, wrap)
            if wrap.binding is None:
                continue
            scope, name = wrap.binding
            if scope in ("global", "def"):
                _merge_callee(callees, name, cal)
            elif scope == "self":
                _merge_callee(self_callees, name, cal)
        for fname, wrap in _forwarders(mod).items():
            _merge_callee(
                callees, fname,
                _Callee(wrap.donate, wrap.static_nums, wrap.static_names, wrap),
            )
        # Local watched bindings stay out of the cross-module registry —
        # a test's `fn = instrument(...)` must not make every `fn()` in
        # the tree look jitted (precision over recall).
        for scope, name in mod.watched:
            blank = _Callee((), (), (), None)
            if scope in ("global", "def"):
                callees.setdefault(name, blank)
            elif scope == "self":
                self_callees.setdefault(name, blank)
    return callees, self_callees


def _judge(mods: Sequence[_ModuleScan]) -> List[Finding]:
    findings: List[Finding] = []
    callees, self_callees = _callee_registry(mods)
    for mod in mods:
        in_test = _is_test_path(mod.path)
        local_by_fn: Dict[str, Dict[str, _Callee]] = {}
        for wrap in mod.wraps:
            if (
                wrap.kind == "jit"
                and wrap.binding
                and wrap.binding[0] == "local"
                and wrap.enclosing
            ):
                local_by_fn.setdefault(wrap.enclosing, {})[wrap.binding[1]] = (
                    _Callee(wrap.donate, wrap.static_nums, wrap.static_names, wrap)
                )
        # Wrap-site rules.
        for wrap in mod.wraps:
            if wrap.kind == "jit" and wrap.fresh_static:
                findings.append(
                    Finding(
                        path=wrap.path, line=wrap.line, col=wrap.col,
                        rule="RT302",
                        message=(
                            "static_argnums/static_argnames computed per "
                            "call — the jit cache keys on a fresh value "
                            "every invocation"
                        ),
                    )
                )
            findings.extend(_judge_rt301(wrap))
            if (
                wrap.kind == "jit"
                and not wrap.registered
                and not in_test
            ):
                findings.append(
                    Finding(
                        path=wrap.path, line=wrap.line, col=wrap.col,
                        rule="RT306",
                        message=(
                            f"jitted program "
                            f"{wrap.target or wrap.binding[1] if wrap.binding else wrap.target or '<anonymous>'} "
                            f"is invisible to the compile watch — wrap it in "
                            f"compile_watch.instrument('<name>', ...) so "
                            f"recompile storms attribute to a named program "
                            f"instead of (unregistered)"
                        ),
                    )
                )
        # Call/use-site rules.
        for rec in mod.funcs:
            judge = _FuncJudge(
                rec,
                callees,
                self_callees,
                local_by_fn.get(rec.qualname, {}),
                findings,
                in_test,
            )
            judge.run()
    return findings


def _judge_rt301(wrap: _Wrap) -> List[Finding]:
    if wrap.kind != "jit":
        return []
    if wrap.in_loop:
        return [
            Finding(
                path=wrap.path, line=wrap.line, col=wrap.col, rule="RT301",
                message=(
                    "jit wrapper constructed inside a loop — it re-traces "
                    "and re-compiles every iteration; hoist the wrapper "
                    "out of the loop"
                ),
            )
        ]
    if wrap.enclosing is None or wrap.returned:
        return []  # module level, or a factory returning the wrapper
    if wrap.binding is not None and wrap.binding[0] in ("global", "def"):
        return []  # lazy module-global cache idiom / decorated def
    fn_name = wrap.enclosing.rsplit(".", 1)[-1].lstrip("_").lower()
    if fn_name.startswith(_ONETIME_PREFIXES) or (
        fn_name.startswith("__") and fn_name.endswith("__")
    ) or fn_name.strip("_") in ("init",):
        return []
    if wrap.binding is not None and wrap.binding[0] == "self":
        enclosed = wrap.enclosing.rsplit(".", 1)[-1]
        if enclosed == "__init__" or enclosed.lstrip("_").startswith(_ONETIME_PREFIXES):
            return []
    return [
        Finding(
            path=wrap.path, line=wrap.line, col=wrap.col, rule="RT301",
            message=(
                f"jit wrapper constructed in the body of "
                f"{wrap.enclosing}() — a fresh wrapper (and compile-cache "
                f"entry) per call; hoist it to module scope or cache it"
            ),
        )
    ]


def _rule_filter(rules: Optional[Iterable[str]]) -> Optional[Set[str]]:
    if rules is None:
        return None
    wanted = {r.upper() for r in rules}
    unknown = wanted - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
    return wanted


def accel_sources(
    sources: Sequence[Tuple[str, str]],
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Analyze a set of (path, source) blobs as one program."""
    only = _rule_filter(rules)
    mods, findings = _scan_all(sources)
    findings = findings + _judge(mods)
    noqa_by_path = {
        mod.path: _parse_noqa(mod.source) for mod in mods
    }
    kept: List[Finding] = []
    for finding in findings:
        if only is not None and finding.rule in RULES and finding.rule not in only:
            continue
        noqa = noqa_by_path.get(finding.path, {})
        suppressed = noqa.get(finding.line)
        if finding.line in noqa and (
            suppressed is None or finding.rule in suppressed
        ):
            continue
        kept.append(finding)
    # Noqa hygiene (RT390) judges the RAW findings, and is itself
    # exempt from suppression — stale suppressions must not be able to
    # suppress their own report.
    if only is None or "RT390" in only:
        for mod in mods:
            kept.extend(
                noqa_hygiene(
                    mod.path,
                    mod.source,
                    findings,
                    family_digit="3",
                    known_ids=set(RULES),
                    hygiene_id="RT390",
                )
            )
    uniq: Dict[Tuple[str, int, str], Finding] = {}
    for f in kept:
        uniq.setdefault((f.path, f.line, f.rule), f)
    out = list(uniq.values())
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def accel_paths(
    paths: Sequence[str], rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    sources, findings = _read_sources(paths)
    findings.extend(accel_sources(sources, rules))
    return findings


def _read_sources(paths: Sequence[str]):
    sources: List[Tuple[str, str]] = []
    findings: List[Finding] = []
    for file_path in _iter_py_files(paths):
        try:
            with open(file_path, "r", encoding="utf-8") as f:
                sources.append((file_path, f.read()))
        except (OSError, UnicodeDecodeError) as e:
            findings.append(
                Finding(
                    path=file_path,
                    line=1,
                    col=1,
                    rule="RT000",
                    message=f"unreadable: {e}",
                )
            )
    return sources, findings


# ---------------------------------------------------------------------------
# inventory (the doctor bridge)
# ---------------------------------------------------------------------------


def build_inventory_sources(sources: Sequence[Tuple[str, str]]) -> dict:
    """Machine-readable program inventory: every wrap site, its program
    name (if registered), and its RT302 hazards.  `compile_watch.
    static_hint()` resolves a live storm's program name against this so
    `rt.diagnose()`'s `verdict.compile` names the static fix site."""
    mods, _ = _scan_all(sources)
    _judge(mods)  # populates wrap.hazards
    programs = []
    for mod in mods:
        for wrap in mod.wraps:
            programs.append(
                {
                    "program": wrap.program,
                    "name_kind": wrap.program_kind,
                    "path": wrap.path,
                    "line": wrap.line,
                    "wrap": wrap.kind,
                    "target": wrap.target or None,
                    "registered": wrap.registered,
                    "donate_argnums": list(wrap.donate),
                    "static_argnums": list(wrap.static_nums),
                    "static_argnames": list(wrap.static_names),
                    "hazards": list(wrap.hazards),
                }
            )
    return {
        "version": 1,
        "programs": programs,
        "unregistered": [
            {"path": p["path"], "line": p["line"], "target": p["target"]}
            for p in programs
            if p["wrap"] == "jit" and not p["registered"]
        ],
    }


def build_inventory(paths: Sequence[str]) -> dict:
    sources, _ = _read_sources(paths)
    return build_inventory_sources(sources)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None, out=None) -> int:
    """CLI body shared by `ray_tpu devtools accel` and `python -m
    ray_tpu.devtools.accel`. Exit codes mirror lint/check/race: 0
    clean, 1 findings, 2 usage/IO errors."""
    out = out if out is not None else sys.stdout
    parser = argparse.ArgumentParser(
        prog="ray_tpu devtools accel",
        description=(
            "accelerator hot-path analyzer (rules RT301-RT306; "
            "suppress with '# rt: noqa[RT3xx]')"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=(
            "files or directories to analyze as ONE program (default: "
            "the installed ray_tpu package)"
        ),
    )
    parser.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="emit findings as a JSON list (CI mode)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    parser.add_argument(
        "--inventory",
        action="store_true",
        help=(
            "emit the program inventory JSON (wrap sites, registration, "
            "RT302 hazards) instead of findings — the doctor bridge"
        ),
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0
    if args.list_rules:
        for rule_id, title in RULES.items():
            print(f"{rule_id}  {title}", file=out)
        return 0
    if not args.paths:
        args.paths = [
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ]
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(
            f"accel: no such path(s): {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2
    if args.inventory:
        print(json.dumps(build_inventory(args.paths), indent=2), file=out)
        return 0
    only = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    try:
        findings = accel_paths(args.paths, only)
    except ValueError as e:
        print(f"accel: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps([asdict(f) for f in findings], indent=2), file=out)
    else:
        for finding in findings:
            print(finding.render(), file=out)
        if findings:
            print(f"{len(findings)} finding(s)", file=out)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
