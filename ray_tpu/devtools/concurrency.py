"""Whole-program concurrency analyzer (`ray_tpu devtools race`,
rules RT201-RT206).

Third devtools layer (after lint's per-file idioms and check's
cross-process contracts): build a thread/lock model of the tree and
judge it for races, deadlocks, and blocking-while-holding — the gate
ROADMAP item 5 (head sharding) and item 2 (multi-tenant scheduler)
are required to pass before touching the contended state tables.

The model, per class:

* **Execution contexts** — which entry points run on which threads.
  A method is a context *root* when it is passed to
  ``threading.Thread(target=...)`` (context ``thread:<name>``), an
  executor ``.submit`` (``executor``), an RPC-server ``.register``
  or named ``_h_*`` (``rpc`` — the server dispatches on a bounded
  pool, so this context is *self-concurrent*), a ``call_async``/
  ``add_done_callback`` callback (``callback`` — runs on the reader
  thread/pool), or ``atexit.register``/``weakref.finalize``/
  ``os.register_at_fork`` (``finalizer``).  ``@rt.remote`` actor
  methods share one ``actor-mailbox`` context (the mailbox is
  single-threaded).  Public methods of a class that owns at least
  one thread root get the ``caller`` context (application threads
  call them while the background machinery runs).  Contexts
  propagate caller→callee over the per-class call graph.
* **Lock attrs** — ``self._x = threading.Lock()/RLock()/Condition()``
  (or the devtools ``make_lock`` witness factory), class-level lock
  attrs, and module-level lock globals; plus *opaque* lock tokens
  for ``with <expr>:`` where the dotted expr looks lock-ish.
* **Guards** — the lock set lexically held at each attribute write,
  widened by the *inherited* lock set: the intersection of locks
  held at every call site of a helper (the ``_foo_locked`` idiom
  stays quiet).  ``with self._hot_lock(...)``-style contextmanager
  methods count as acquiring whatever they lexically acquire.

| id    | judgment                                                     |
|-------|--------------------------------------------------------------|
| RT201 | attribute written from ≥2 execution contexts (or one        |
|       | self-concurrent context) with no common lock.  Attrs whose  |
|       | every write is a plain constant store (``self._stop=True``) |
|       | are exempt — single STORE_ATTR ops are GIL-atomic flags.    |
| RT202 | lock-order-inversion cycle in the static acquisition graph  |
|       | (A held while taking B somewhere, B held while taking A     |
|       | elsewhere); also a plain ``Lock`` re-acquired while held    |
|       | (self-deadlock — RLocks are exempt).                        |
| RT203 | blocking call (``time.sleep``, ``rt.get``, ``.result()``,   |
|       | ``.recv()``, ``.accept()``, ``client.call(...)`` RPCs,      |
|       | queue ``.get/.put(timeout=)``, thread ``.join()``) while    |
|       | holding a lock — the daemon ``_hot_lock`` discipline,       |
|       | generalized.                                                 |
| RT204 | ``Condition.wait()`` outside a predicate loop (wakeups are  |
|       | spurious and racy by spec).                                 |
| RT205 | lock created per-call in a function body and only used      |
|       | there — a fresh lock per invocation guards nothing.         |
| RT206 | finalizer/atexit/fork callback (or ``__del__``) that        |
|       | acquires a lock — runs on an arbitrary thread that may      |
|       | already hold it (the post-fork reset idiom must stay        |
|       | lock-free).                                                  |

Shares the lint/check contract: ``# rt: noqa[RT2xx]`` suppressions,
``--json``, exit 0 clean / 1 findings / 2 usage errors.  Precision
over recall throughout: cross-class context flow, aliased locks, and
dynamically-chosen attributes stay silent rather than guessing — the
runtime counterpart (`devtools/lock_witness.py`) supplies the dynamic
evidence this pass cannot see.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .contracts import build_symbol_table
from .lint import (
    Finding,
    _dotted,
    _is_remote_decorator,
    _iter_py_files,
    noqa_hygiene,
)

__all__ = ["race_sources", "race_paths", "main", "RULES"]

#: id -> one-line title (the --list-rules table).
RULES: Dict[str, str] = {
    "RT201": "attribute written from ≥2 contexts with no common lock",
    "RT202": "lock-order inversion cycle in the acquisition graph",
    "RT203": "blocking call while holding a lock",
    "RT204": "Condition.wait() outside a predicate loop",
    "RT205": "per-call lock guards nothing",
    "RT206": "finalizer/__del__ acquires a lock on an arbitrary thread",
    "RT290": "stale or unknown '# rt: noqa' suppression (race family)",
}

#: Constructors that create a mutex-like object -> kind.
_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
}

#: The witness factory (devtools/lock_witness.py): make_lock(name,
#: kind=...) returns a Lock/RLock — analyzed like the raw ctor.
_LOCK_FACTORIES = {"make_lock", "lock_witness.make_lock"}

_THREAD_CTORS = {"threading.Thread", "Thread"}

#: Context labels where two invocations of the SAME root can run
#: concurrently (dispatch pools, reader threads, GC/atexit threads) —
#: one such context already counts as two for RT201.
_SELF_CONCURRENT = {"rpc", "callback", "executor", "finalizer"}

#: Dotted names that block the calling thread.
_BLOCKING_DOTTED = {"time.sleep", "rt.get", "ray_tpu.get", "select.select",
                    "subprocess.run", "subprocess.check_output"}

#: Attribute calls that block regardless of kwargs.
_BLOCKING_ATTRS = {"result", "recv", "recv_into", "accept", "communicate"}

#: Methods that mutate their receiver in place.
_MUTATORS = {
    "append", "extend", "add", "discard", "remove", "pop", "popleft",
    "appendleft", "clear", "update", "setdefault", "insert",
}

#: Substrings marking a dotted with-target as "probably a mutex" when
#: we cannot resolve its constructor (opaque tokens).
_LOCKISH = ("lock", "mutex", "cond", "gate")


def _name_of(expr: ast.expr) -> Optional[str]:
    return _dotted(expr)


def _is_lock_ctor(call: ast.Call) -> Optional[str]:
    """Kind string when `call` constructs a mutex, else None."""
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    if dotted in _LOCK_CTORS:
        return _LOCK_CTORS[dotted]
    if dotted in _LOCK_FACTORIES or dotted.endswith(".make_lock"):
        for kw in call.keywords:
            if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                return str(kw.value.value)
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
            return str(call.args[1].value)
        return "lock"
    return None


@dataclass
class _Write:
    attr: str
    line: int
    col: int
    held: frozenset
    atomic: bool  # plain constant store (GIL-atomic flag)


@dataclass
class _Acquire:
    token: str
    kind: Optional[str]
    line: int
    col: int
    held: frozenset  # tokens already held when this one is taken


@dataclass
class _Blocking:
    line: int
    col: int
    what: str
    held: frozenset


@dataclass
class _SelfCall:
    callee: str  # method name within the same class (or mangled local)
    line: int
    col: int
    held: frozenset


@dataclass
class _FuncInfo:
    name: str  # method name; nested defs/lambdas are "outer.<name>"
    qualname: str
    path: str
    line: int
    roots: Set[str] = field(default_factory=set)
    writes: List[_Write] = field(default_factory=list)
    acquires: List[_Acquire] = field(default_factory=list)
    blocking: List[_Blocking] = field(default_factory=list)
    calls: List[_SelfCall] = field(default_factory=list)
    cond_waits: List[Tuple[str, int, int, bool]] = field(
        default_factory=list
    )  # (token, line, col, in_loop)
    findings: List[Finding] = field(default_factory=list)  # RT205/206


@dataclass
class _ClassModel:
    name: str
    path: str
    line: int
    is_actor: bool = False
    #: attr -> kind for self._x = Lock()/RLock()/Condition() (instance
    #: or class level).
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    #: attrs assigned threading.Thread(...) — lets RT203 flag
    #: `self._t.join()` without flagging `", ".join(...)`.
    thread_attrs: Set[str] = field(default_factory=set)
    #: contextmanager methods -> lock tokens they lexically acquire
    #: (the daemon `_hot_lock` idiom).
    cm_locks: Dict[str, Set[Tuple[str, Optional[str]]]] = field(
        default_factory=dict
    )
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    funcs: Dict[str, _FuncInfo] = field(default_factory=dict)


class _Model:
    """Phase-1 output: every class + module-level function scanned."""

    def __init__(self) -> None:
        self.classes: List[_ClassModel] = []
        #: path -> {name: kind} module-level lock globals.
        self.module_locks: Dict[str, Dict[str, str]] = {}
        #: module-level (and pseudo-class-less) funcs, per path.
        self.module_funcs: Dict[str, List[_FuncInfo]] = {}


# ---------------------------------------------------------------------------
# phase 1: build the thread/lock model
# ---------------------------------------------------------------------------


def _is_contextmanager(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        dotted = _dotted(dec) or ""
        if dotted.endswith("contextmanager"):
            return True
    return False


def _scan_class(path: str, node: ast.ClassDef, model: _Model) -> None:
    cm = _ClassModel(name=node.name, path=path, line=node.lineno)
    cm.is_actor = any(
        _is_remote_decorator(d) for d in node.decorator_list
    )
    # Pass A: collect methods, lock/thread attrs (class body + any
    # `self._x = <lock ctor>` in any method — usually __init__).
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cm.methods[item.name] = item
        elif isinstance(item, ast.Assign) and isinstance(
            item.value, ast.Call
        ):
            kind = _is_lock_ctor(item.value)
            if kind:
                for tgt in item.targets:
                    if isinstance(tgt, ast.Name):
                        cm.lock_attrs[tgt.id] = kind
    for method in cm.methods.values():
        for sub in ast.walk(method):
            if not (
                isinstance(sub, ast.Assign)
                and isinstance(sub.value, ast.Call)
            ):
                continue
            for tgt in sub.targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in ("self", "cls")
                ):
                    continue
                kind = _is_lock_ctor(sub.value)
                if kind:
                    cm.lock_attrs.setdefault(tgt.attr, kind)
                elif _dotted(sub.value.func) in _THREAD_CTORS:
                    cm.thread_attrs.add(tgt.attr)
    # Pass B: contextmanager methods' lexical lock sets, so
    # `with self._hot_lock(...)` counts as holding self._lock.
    for name, method in cm.methods.items():
        if not _is_contextmanager(method):
            continue
        tokens: Set[Tuple[str, Optional[str]]] = set()
        for sub in ast.walk(method):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    tok = _lock_token(
                        item.context_expr, cm, model.module_locks.get(path, {})
                    )
                    if tok:
                        tokens.add(tok)
        if tokens:
            cm.cm_locks[name] = tokens
    # Pass C: scan every method body.
    for name, method in cm.methods.items():
        _scan_function(path, name, method, cm, model)
    model.classes.append(cm)


def _lock_token(
    expr: ast.expr,
    cm: Optional[_ClassModel],
    module_locks: Dict[str, str],
) -> Optional[Tuple[str, Optional[str]]]:
    """(token, kind) when `expr` names a mutex, else None.

    Known tokens are class- or module-qualified; opaque lock-ish
    dotted expressions get a textual token (stable within one class,
    excluded from the global RT202 graph).
    """
    if isinstance(expr, ast.Call):
        # `with self._hot_lock("dispatch"):` — a contextmanager
        # method that acquires locks; resolved by the caller via
        # cm.cm_locks (cannot return multiple tokens here).
        return None
    dotted = _dotted(expr)
    if dotted is None:
        return None
    if cm is not None and "." in dotted:
        recv, _, attr = dotted.rpartition(".")
        if recv in ("self", "cls", cm.name) and attr in cm.lock_attrs:
            return (f"{cm.name}.{attr}", cm.lock_attrs[attr])
    if dotted in module_locks:
        return (dotted, module_locks[dotted])
    low = dotted.lower()
    if any(s in low for s in _LOCKISH):
        scope = cm.name if cm is not None else "<module>"
        if dotted.startswith(("self.", "cls.")):
            return (f"{scope}.{dotted.split('.', 1)[1]}", None)
        return (f"{scope}:{dotted}", None)
    return None


class _FuncScanner(ast.NodeVisitor):
    """One function body: held-lock-aware event collection.

    Does not descend into nested defs/lambdas — those are scanned as
    their own (mangled) _FuncInfo, and a local name map lets
    `Thread(target=loop)` mark the nested def as a root.
    """

    def __init__(
        self,
        path: str,
        info: _FuncInfo,
        cm: Optional[_ClassModel],
        model: _Model,
        local_conds: Set[str],
    ) -> None:
        self.path = path
        self.info = info
        self.cm = cm
        self.model = model
        self.module_locks = model.module_locks.get(path, {})
        self.held: List[Tuple[str, Optional[str]]] = []
        self.loop_depth = 0
        #: local var -> (kind, line, col) for `x = threading.Lock()`.
        self.local_locks: Dict[str, Tuple[str, int, int]] = {}
        self.local_lock_with: Dict[str, int] = {}
        self.local_lock_escaped: Set[str] = set()
        self.local_conds = local_conds
        #: local def name -> mangled _FuncInfo name.
        self.local_defs: Dict[str, str] = {}
        self._nested = 0

    # -- held-set helpers ------------------------------------------------

    def _held(self) -> frozenset:
        return frozenset(tok for tok, _ in self.held)

    def _tokens_for(self, expr: ast.expr) -> List[Tuple[str, Optional[str]]]:
        if isinstance(expr, ast.Call):
            dotted = _dotted(expr.func) or ""
            if (
                self.cm is not None
                and dotted.startswith("self.")
                and dotted[5:] in self.cm.cm_locks
            ):
                return sorted(
                    self.cm.cm_locks[dotted[5:]], key=lambda t: t[0]
                )
            return []
        if isinstance(expr, ast.Name) and expr.id in self.local_locks:
            kind, _, _ = self.local_locks[expr.id]
            self.local_lock_with[expr.id] = expr.lineno
            return [(f"<local>.{expr.id}", kind)]
        tok = _lock_token(expr, self.cm, self.module_locks)
        return [tok] if tok else []

    # -- visitors --------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired: List[Tuple[str, Optional[str]]] = []
        for item in node.items:
            for tok in self._tokens_for(item.context_expr):
                self.info.acquires.append(
                    _Acquire(
                        token=tok[0],
                        kind=tok[1],
                        line=node.lineno,
                        col=node.col_offset + 1,
                        held=self._held(),
                    )
                )
                self.held.append(tok)
                acquired.append(tok)
            if isinstance(item.context_expr, ast.Call):
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_For(self, node: ast.For) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_AsyncFor = visit_For

    def visit_While(self, node: ast.While) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        mangled = f"{self.info.name}.{node.name}"
        self.local_defs[node.name] = mangled
        # Locals captured by a closure escape the call (the closure
        # may be handed to another thread — the lock then DOES guard).
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.local_locks:
                self.local_lock_escaped.add(sub.id)
        _scan_function(self.path, mangled, node, self.cm, self.model)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.local_locks:
                self.local_lock_escaped.add(sub.id)
        # Lambda bodies are scanned only when registered as callbacks
        # (handled in visit_Call); a bare lambda is inert here.

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            kind = _is_lock_ctor(node.value)
            if kind and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                self.local_locks[node.targets[0].id] = (
                    kind,
                    node.lineno,
                    node.col_offset + 1,
                )
        atomic = isinstance(node.value, ast.Constant)
        for tgt in node.targets:
            self._record_store(tgt, atomic)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_store(node.target, atomic=False)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_store(
                node.target, atomic=isinstance(node.value, ast.Constant)
            )
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._record_store(tgt, atomic=False)

    def _record_store(self, tgt: ast.expr, atomic: bool) -> None:
        # self.X = v (atomic store), self.X[k] = v / del self.X[k]
        # (container mutation — never atomic for judgment purposes).
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._record_store(el, atomic=False)
            return
        if isinstance(tgt, ast.Subscript):
            tgt, atomic = tgt.value, False
        if (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id in ("self", "cls")
        ):
            self.info.writes.append(
                _Write(
                    attr=tgt.attr,
                    line=tgt.lineno,
                    col=tgt.col_offset + 1,
                    held=self._held(),
                    atomic=atomic,
                )
            )

    def visit_Call(self, node: ast.Call) -> None:  # noqa: C901
        dotted = _dotted(node.func) or ""
        # `pool().submit(fn)` has no dotted name — the method name
        # alone still identifies the callback-handoff / blocking verb.
        tail = (
            node.func.attr
            if isinstance(node.func, ast.Attribute)
            else dotted
        )
        self._maybe_register_root(node, dotted, tail)
        self._maybe_blocking(node, dotted, tail)
        self._maybe_acquire_release(node, dotted)
        self._maybe_self_call(node, dotted)
        self._maybe_mutator(node, dotted)
        self._maybe_cond_wait(node, dotted)
        self._maybe_escape(node)
        self.generic_visit(node)

    def _callback_label(
        self, dotted: str, tail: str
    ) -> Optional[Tuple[str, int]]:
        """(context label, arg index of the callable) for calls that
        hand a callable to another execution context."""
        if dotted in _THREAD_CTORS:
            return ("thread", -1)  # target= kwarg
        if tail == "submit":
            return ("executor", 0)
        if tail == "register" and dotted.startswith("atexit"):
            return ("finalizer", 0)
        if tail == "register_at_fork":
            return ("finalizer", -2)  # kwargs only
        if tail == "finalize" and "weakref" in dotted:
            return ("finalizer", 1)
        if tail == "register":
            return ("rpc", 1)
        if tail == "call_async":
            return ("callback", 1)
        if tail == "add_done_callback":
            return ("callback", 0)
        return None

    def _maybe_register_root(
        self, node: ast.Call, dotted: str, tail: str
    ) -> None:
        spec = self._callback_label(dotted, tail)
        if spec is None:
            return
        label, idx = spec
        candidates: List[ast.expr] = []
        if idx == -1:  # Thread(target=...)
            for kw in node.keywords:
                if kw.arg == "target":
                    candidates.append(kw.value)
        elif idx == -2:  # register_at_fork(**kwargs)
            candidates.extend(kw.value for kw in node.keywords if kw.arg)
        else:
            if len(node.args) > idx:
                candidates.append(node.args[idx])
            for kw in node.keywords:
                if kw.arg in ("callback", "fn", "func", "target"):
                    candidates.append(kw.value)
        for cand in candidates:
            self._mark_root(cand, label, node.lineno)

    def _mark_root(self, expr: ast.expr, label: str, line: int) -> None:
        cd = _dotted(expr) or ""
        if cd.startswith(("functools.partial", "partial")) and isinstance(
            expr, ast.Call
        ):
            if expr.args:
                self._mark_root(expr.args[0], label, line)
            return
        if isinstance(expr, ast.Lambda):
            mangled = f"{self.info.name}.<lambda>L{expr.lineno}"
            wrapper = ast.FunctionDef(
                name=mangled,
                args=expr.args,
                body=[ast.Expr(value=expr.body)],
                decorator_list=[],
                returns=None,
            )
            ast.copy_location(wrapper, expr)
            ast.fix_missing_locations(wrapper)
            _scan_function(self.path, mangled, wrapper, self.cm, self.model)
            self._root_for(mangled, label)
            return
        if cd.startswith("self.") and self.cm is not None:
            name = cd[5:]
            if name in self.cm.methods:
                self._root_for(name, label)
            return
        if cd in self.local_defs:
            self._root_for(self.local_defs[cd], label)

    def _root_for(self, func_name: str, label: str) -> None:
        if label == "thread":
            label = f"thread:{func_name.rpartition('.')[2]}"
        bucket = (
            self.cm.funcs
            if self.cm is not None
            else {f.name: f for f in self.model.module_funcs.get(self.path, [])}
        )
        info = bucket.get(func_name)
        if info is not None:
            info.roots.add(label)
        else:
            # Scanned later (forward reference to a sibling method):
            # park the root on a pending map via the class model.
            if self.cm is not None:
                self.cm.funcs.setdefault(
                    func_name,
                    _FuncInfo(
                        name=func_name,
                        qualname=func_name,
                        path=self.path,
                        line=0,
                    ),
                ).roots.add(label)

    def _maybe_blocking(
        self, node: ast.Call, dotted: str, tail: str
    ) -> None:
        what = None
        is_attr = isinstance(node.func, ast.Attribute)
        if dotted in _BLOCKING_DOTTED:
            what = dotted
        elif tail in _BLOCKING_ATTRS and is_attr:
            what = f".{tail}()"
        elif tail == "call" and is_attr and node.args and isinstance(
            node.args[0], ast.Constant
        ):
            what = f'.call("{node.args[0].value}") RPC'
        elif tail in ("get", "put") and is_attr and any(
            kw.arg in ("timeout", "block")
            # timeout=0 / block=False are explicit NON-blocking forms.
            and not (
                isinstance(kw.value, ast.Constant) and not kw.value.value
            )
            for kw in node.keywords
        ):
            what = f".{tail}(timeout=...)"
        elif tail == "join" and self.cm is not None and "." in dotted:
            recv = dotted.rpartition(".")[0]
            if (
                recv.startswith(("self.", "cls."))
                and recv.split(".", 1)[1] in self.cm.thread_attrs
            ):
                what = ".join() on a thread"
        elif tail == "wait":
            recv = dotted.rpartition(".")[0]
            tok = (
                _lock_token(
                    ast.parse(recv, mode="eval").body
                    if recv
                    else ast.Name(id=""),
                    self.cm,
                    self.module_locks,
                )
                if recv
                else None
            )
            # Waiting on the condition you hold RELEASES it; waiting
            # on anything else (Event, other cond) while holding a
            # DIFFERENT lock blocks with it held.
            if tok is not None and tok[0] not in self._held():
                what = f"{recv}.wait()"
        if what is not None:
            self.info.blocking.append(
                _Blocking(
                    line=node.lineno,
                    col=node.col_offset + 1,
                    what=what,
                    held=self._held(),
                )
            )

    def _maybe_acquire_release(self, node: ast.Call, dotted: str) -> None:
        tail = dotted.rpartition(".")[2]
        if tail != "acquire" or "." not in dotted:
            return
        recv = dotted.rpartition(".")[0]
        try:
            expr = ast.parse(recv, mode="eval").body
        except SyntaxError:
            return
        for tok in self._tokens_for(expr) or (
            [(f"<local>.{recv}", self.local_locks[recv][0])]
            if recv in self.local_locks
            else []
        ):
            self.info.acquires.append(
                _Acquire(
                    token=tok[0],
                    kind=tok[1],
                    line=node.lineno,
                    col=node.col_offset + 1,
                    held=self._held(),
                )
            )

    def _maybe_self_call(self, node: ast.Call, dotted: str) -> None:
        if self.cm is not None and dotted.startswith("self."):
            name = dotted[5:]
            if name in self.cm.methods:
                self.info.calls.append(
                    _SelfCall(
                        callee=name,
                        line=node.lineno,
                        col=node.col_offset + 1,
                        held=self._held(),
                    )
                )
                return
        if dotted in self.local_defs:
            self.info.calls.append(
                _SelfCall(
                    callee=self.local_defs[dotted],
                    line=node.lineno,
                    col=node.col_offset + 1,
                    held=self._held(),
                )
            )

    def _maybe_mutator(self, node: ast.Call, dotted: str) -> None:
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in _MUTATORS:
            return
        recv = node.func.value
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id in ("self", "cls")
        ):
            self.info.writes.append(
                _Write(
                    attr=recv.attr,
                    line=node.lineno,
                    col=node.col_offset + 1,
                    held=self._held(),
                    atomic=False,
                )
            )

    def _maybe_cond_wait(self, node: ast.Call, dotted: str) -> None:
        tail = dotted.rpartition(".")[2]
        if tail not in ("wait", "wait_for"):
            return
        recv = dotted.rpartition(".")[0]
        is_cond = False
        if recv.startswith(("self.", "cls.")) and self.cm is not None:
            is_cond = (
                self.cm.lock_attrs.get(recv.split(".", 1)[1]) == "condition"
            )
        elif recv in self.local_conds:
            is_cond = True
        if is_cond and tail == "wait":
            self.info.cond_waits.append(
                (
                    recv,
                    node.lineno,
                    node.col_offset + 1,
                    self.loop_depth > 0,
                )
            )

    def _maybe_escape(self, node: ast.Call) -> None:
        # A local lock passed anywhere / returned / stored escapes
        # per-call scope and is NOT an RT205 case.
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in self.local_locks:
                self.local_lock_escaped.add(arg.id)

    def visit_Return(self, node: ast.Return) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.local_locks:
                self.local_lock_escaped.add(sub.id)
        self.generic_visit(node)


def _scan_function(
    path: str,
    name: str,
    node: ast.AST,
    cm: Optional[_ClassModel],
    model: _Model,
) -> None:
    qual = f"{cm.name}.{name}" if cm is not None else name
    existing = cm.funcs.get(name) if cm is not None else None
    info = existing or _FuncInfo(
        name=name, qualname=qual, path=path, line=node.lineno
    )
    info.qualname, info.line = qual, node.lineno
    local_conds = {
        tgt.id
        for sub in ast.walk(node)
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call)
        for tgt in sub.targets
        if isinstance(tgt, ast.Name)
        and _is_lock_ctor(sub.value) == "condition"
    }
    scanner = _FuncScanner(path, info, cm, model, local_conds)
    for stmt in node.body:
        scanner.visit(stmt)
    # RT205: a per-call lock with-used here that never escaped.
    for var, (kind, line, col) in scanner.local_locks.items():
        if (
            var in scanner.local_lock_with
            and var not in scanner.local_lock_escaped
            and name not in ("__init__",)
        ):
            info.findings.append(
                Finding(
                    path=path,
                    line=line,
                    col=col,
                    rule="RT205",
                    message=(
                        f"{qual} creates {kind} '{var}' per call and only "
                        f"uses it locally — a fresh lock each invocation "
                        f"guards nothing (make it an instance/module "
                        f"attribute)"
                    ),
                )
            )
    # RT206: __del__ acquiring a lock.
    if name == "__del__" and info.acquires:
        acq = info.acquires[0]
        info.findings.append(
            Finding(
                path=path,
                line=acq.line,
                col=acq.col,
                rule="RT206",
                message=(
                    f"{qual} acquires {acq.token} — __del__ runs on "
                    f"whatever thread drops the last reference, which may "
                    f"already hold it (deadlock); use weakref.finalize "
                    f"with lock-free cleanup"
                ),
            )
        )
    if cm is not None:
        cm.funcs[name] = info
    else:
        bucket = model.module_funcs.setdefault(path, [])
        if info not in bucket:
            bucket.append(info)


def _build_model(
    sources: Sequence[Tuple[str, str]],
    parsed: Sequence,
) -> _Model:
    model = _Model()
    # Module-level locks first (so class scans can resolve them).
    for pf in parsed:
        locks: Dict[str, str] = {}
        for node in pf.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                kind = _is_lock_ctor(node.value)
                if kind:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            locks[tgt.id] = kind
        model.module_locks[pf.path] = locks
    for pf in parsed:
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                _scan_class(pf.path, node, model)
        for node in pf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_function(pf.path, node.name, node, None, model)
    return model


# ---------------------------------------------------------------------------
# phase 2: judgments
# ---------------------------------------------------------------------------


def _propagate(cm: _ClassModel) -> Tuple[Dict[str, Set[str]], Dict[str, frozenset]]:
    """(contexts per func, inherited-lock set per func).

    Contexts flow caller→callee (union, increasing fixpoint);
    inherited locks are the intersection over all call sites of
    (lexically held there ∪ caller's own inherited set) — the
    `_foo_locked` helper idiom.  Root functions inherit nothing.
    """
    # RPC handlers by naming convention (`getattr(self, "_h_"+name)`
    # registration loops make the explicit .register edge invisible).
    for name, info in cm.funcs.items():
        base = name.rpartition(".")[2]
        if base.startswith("_h_"):
            info.roots.add("rpc")
    if cm.is_actor:
        for name, info in cm.funcs.items():
            if not name.startswith("_") and "." not in name:
                info.roots.add("actor-mailbox")
    concurrent_roots = any(
        lbl for f in cm.funcs.values() for lbl in f.roots
        if lbl != "actor-mailbox"
    )
    if concurrent_roots and not cm.is_actor:
        for name, info in cm.funcs.items():
            if (
                "." not in name
                and not name.startswith("_")
            ):
                info.roots.add("caller")
    contexts: Dict[str, Set[str]] = {
        n: set(f.roots) for n, f in cm.funcs.items()
    }
    callers: Dict[str, List[Tuple[str, frozenset]]] = {}
    for name, info in cm.funcs.items():
        for call in info.calls:
            callers.setdefault(call.callee, []).append((name, call.held))
    for _ in range(len(cm.funcs) + 2):
        changed = False
        for name, info in cm.funcs.items():
            for call in info.calls:
                tgt = contexts.get(call.callee)
                if tgt is not None and not contexts[name] <= tgt:
                    tgt |= contexts[name]
                    changed = True
        if not changed:
            break
    TOP = frozenset({"<top>"})
    inherited: Dict[str, frozenset] = {
        n: (frozenset() if f.roots else TOP) for n, f in cm.funcs.items()
    }
    for _ in range(len(cm.funcs) + 2):
        changed = False
        for name, info in cm.funcs.items():
            if info.roots:
                continue
            sites = callers.get(name, [])
            if not sites:
                continue
            meet: Optional[frozenset] = None
            for caller, held in sites:
                inh = inherited.get(caller, frozenset())
                eff = held | (frozenset() if inh == TOP else inh)
                meet = eff if meet is None else (meet & eff)
            meet = meet if meet is not None else frozenset()
            if meet != inherited[name]:
                inherited[name] = meet
                changed = True
        if not changed:
            break
    inherited = {
        n: (frozenset() if v == TOP else v) for n, v in inherited.items()
    }
    return contexts, inherited


def _ctx_weight(ctx: Set[str]) -> int:
    return len(ctx) + sum(1 for c in ctx if c in _SELF_CONCURRENT)


def _judge_class(cm: _ClassModel, findings: List[Finding]) -> None:
    contexts, inherited = _propagate(cm)
    for info in cm.funcs.values():
        findings.extend(info.findings)
        for token, line, col, in_loop in info.cond_waits:
            if not in_loop:
                findings.append(
                    Finding(
                        path=info.path,
                        line=line,
                        col=col,
                        rule="RT204",
                        message=(
                            f"{info.qualname} calls {token}.wait() outside "
                            f"a predicate loop — condition wakeups are "
                            f"spurious by spec; use `while not <pred>: "
                            f"{token}.wait(...)`"
                        ),
                    )
                )
    # RT203: blocking while holding (lexical ∪ inherited locks).
    blocked_lines: Set[Tuple[str, int]] = set()
    for name, info in cm.funcs.items():
        inh = inherited.get(name, frozenset())
        for blk in info.blocking:
            held = blk.held | inh
            if held:
                blocked_lines.add((info.path, blk.line))
                findings.append(
                    Finding(
                        path=info.path,
                        line=blk.line,
                        col=blk.col,
                        rule="RT203",
                        message=(
                            f"{info.qualname} calls {blk.what} while "
                            f"holding {', '.join(sorted(held))} — move the "
                            f"blocking call outside the lock (the "
                            f"_hot_lock discipline)"
                        ),
                    )
                )
    # One-level transitive RT203: `self.m()` under a lock where m
    # lexically blocks (reported at the call site, naming both sides).
    direct_block: Dict[str, Optional[_Blocking]] = {
        n: next((b for b in f.blocking if not b.held), None)
        for n, f in cm.funcs.items()
    }
    for name, info in cm.funcs.items():
        for call in info.calls:
            blk = direct_block.get(call.callee)
            if blk is None or not call.held:
                continue
            if (info.path, call.line) in blocked_lines:
                continue
            callee = cm.funcs[call.callee]
            if inherited.get(call.callee):
                continue  # already reported at the callee
            findings.append(
                Finding(
                    path=info.path,
                    line=call.line,
                    col=call.col,
                    rule="RT203",
                    message=(
                        f"{info.qualname} holds "
                        f"{', '.join(sorted(call.held))} while calling "
                        f"self.{call.callee.rpartition('.')[2]}(), which "
                        f"blocks on {blk.what} at "
                        f"{callee.path}:{blk.line}"
                    ),
                )
            )
    # RT201: shared-attr writes across contexts with no common lock.
    if not any(
        lbl
        for f in cm.funcs.values()
        for lbl in f.roots
        if lbl not in ("caller",)
    ):
        return
    by_attr: Dict[str, List[Tuple[_FuncInfo, _Write, Set[str], frozenset]]] = {}
    for name, info in cm.funcs.items():
        ctx = contexts.get(name, set())
        if not ctx:
            continue  # unreachable from any entry point
        base = name.rpartition(".")[2]
        if base in ("__init__", "__new__", "__del__"):
            continue
        inh = inherited.get(name, frozenset())
        for wr in info.writes:
            by_attr.setdefault(wr.attr, []).append(
                (info, wr, ctx, wr.held | inh)
            )
    for attr, sites in sorted(by_attr.items()):
        if attr in cm.lock_attrs:
            continue
        if all(wr.atomic for _, wr, _, _ in sites):
            continue  # constant flag stores are GIL-atomic
        all_ctx: Set[str] = set()
        for _, _, ctx, _ in sites:
            all_ctx |= ctx
        if all_ctx == {"actor-mailbox"}:
            continue  # mailbox is single-threaded
        if _ctx_weight(all_ctx) < 2:
            continue
        common = None
        for _, _, _, held in sites:
            common = held if common is None else (common & held)
        if common:
            continue
        info, wr, _, _ = sites[0]
        others = "; ".join(
            f"{i.path}:{w.line} in {i.qualname} "
            f"[{'/'.join(sorted(c))}]"
            + (f" holding {'/'.join(sorted(h))}" if h else " unlocked")
            for i, w, c, h in sites[:4]
        )
        findings.append(
            Finding(
                path=info.path,
                line=wr.line,
                col=wr.col,
                rule="RT201",
                message=(
                    f"{cm.name}.{attr} is written from contexts "
                    f"{{{', '.join(sorted(all_ctx))}}} with no common "
                    f"lock — sites: {others}"
                ),
            )
        )


def _lock_graph(model: _Model) -> List[Finding]:
    """RT202: cycles in the global acquisition-order graph."""
    findings: List[Finding] = []
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    kinds: Dict[str, Optional[str]] = {}

    def _known(token: str) -> bool:
        # Opaque tokens ("Class.self._x?"-style or heuristic) stay out
        # of the global graph: identity across files is a guess.
        return "<local>" not in token and ":" not in token

    all_funcs: List[Tuple[Optional[_ClassModel], _FuncInfo]] = []
    for cm in model.classes:
        for info in cm.funcs.values():
            all_funcs.append((cm, info))
    for infos in model.module_funcs.values():
        for info in infos:
            all_funcs.append((None, info))
    direct_acq: Dict[str, Set[str]] = {}
    for cm, info in all_funcs:
        key = info.qualname if cm is None else f"{cm.name}.{info.name}"
        direct_acq[key] = {a.token for a in info.acquires if _known(a.token)}
    for cm, info in all_funcs:
        for acq in info.acquires:
            kinds.setdefault(acq.token, acq.kind)
            if not _known(acq.token):
                continue
            # RLock re-entry is legal; a plain Lock taken while held
            # is an instant self-deadlock.
            if acq.token in acq.held:
                if kinds.get(acq.token) == "lock":
                    findings.append(
                        Finding(
                            path=info.path,
                            line=acq.line,
                            col=acq.col,
                            rule="RT202",
                            message=(
                                f"{info.qualname} re-acquires "
                                f"non-reentrant Lock {acq.token} while "
                                f"already holding it — self-deadlock"
                            ),
                        )
                    )
                continue
            for held in acq.held:
                if _known(held) and held != acq.token:
                    edges.setdefault(
                        (held, acq.token),
                        (info.path, acq.line, info.qualname),
                    )
        # One-level call edges: holding A while calling m() which
        # lexically acquires B.
        for call in info.calls:
            if not call.held:
                continue
            callee_key = (
                f"{cm.name}.{call.callee}" if cm is not None else call.callee
            )
            for tgt in direct_acq.get(callee_key, ()):
                for held in call.held:
                    if _known(held) and held != tgt:
                        edges.setdefault(
                            (held, tgt),
                            (info.path, call.line, info.qualname),
                        )
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    seen_cycles: Set[frozenset] = set()
    for a, b in sorted(edges):
        # Short inversion cycles (length 2..4) via bounded DFS b→a.
        stack = [(b, [b])]
        while stack:
            node, path_ = stack.pop()
            if len(path_) > 4:
                continue
            for nxt in sorted(adj.get(node, ())):
                if nxt == a:
                    cyc = frozenset(path_ + [a])
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    order = [a] + path_
                    legs = []
                    for i, lock in enumerate(order):
                        nxt_lock = order[(i + 1) % len(order)]
                        site = edges.get((lock, nxt_lock))
                        if site:
                            legs.append(
                                f"{lock}->{nxt_lock} at "
                                f"{site[0]}:{site[1]} ({site[2]})"
                            )
                    path0, line0, _ = edges[(a, b)]
                    findings.append(
                        Finding(
                            path=path0,
                            line=line0,
                            col=1,
                            rule="RT202",
                            message=(
                                "lock-order inversion: "
                                + "; ".join(legs)
                                + " — a thread on each side deadlocks"
                            ),
                        )
                    )
                elif nxt not in path_:
                    stack.append((nxt, path_ + [nxt]))
    return findings


# ---------------------------------------------------------------------------
# RT206 (registration-side): callbacks handed to finalizer contexts
# that acquire locks — judged after propagation so the callback's own
# acquisitions are known.
# ---------------------------------------------------------------------------


def _finalizer_findings(model: _Model) -> List[Finding]:
    findings: List[Finding] = []
    for cm in model.classes:
        for info in cm.funcs.values():
            if "finalizer" not in info.roots:
                continue
            for acq in info.acquires:
                findings.append(
                    Finding(
                        path=info.path,
                        line=acq.line,
                        col=acq.col,
                        rule="RT206",
                        message=(
                            f"{info.qualname} runs as a finalizer/atexit/"
                            f"fork callback but acquires {acq.token} — "
                            f"the callback fires on an arbitrary thread "
                            f"that may already hold it (the post-fork "
                            f"reset idiom must stay lock-free)"
                        ),
                    )
                )
                break  # one finding per callback is enough
    for infos in model.module_funcs.values():
        for info in infos:
            if "finalizer" not in info.roots:
                continue
            for acq in info.acquires:
                findings.append(
                    Finding(
                        path=info.path,
                        line=acq.line,
                        col=acq.col,
                        rule="RT206",
                        message=(
                            f"{info.qualname} runs as a finalizer/atexit/"
                            f"fork callback but acquires {acq.token} — "
                            f"the callback fires on an arbitrary thread "
                            f"that may already hold it"
                        ),
                    )
                )
                break
    return findings


# ---------------------------------------------------------------------------
# drivers (same contract as lint/check)
# ---------------------------------------------------------------------------


def race_sources(
    sources: Sequence[Tuple[str, str]],
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Analyze a set of (path, source) blobs as one program."""
    only = _rule_filter(rules)
    table = build_symbol_table(sources)
    findings: List[Finding] = []
    parsed_paths = {pf.path for pf in table.files}
    for path, source in sources:
        if path not in parsed_paths:
            try:
                ast.parse(source, filename=path)
            except SyntaxError as e:
                findings.append(
                    Finding(
                        path=path,
                        line=e.lineno or 1,
                        col=(e.offset or 0) + 1,
                        rule="RT000",
                        message=f"file does not parse: {e.msg}",
                    )
                )
    model = _build_model(sources, table.files)
    for cm in model.classes:
        _judge_class(cm, findings)
    for infos in model.module_funcs.values():
        for info in infos:
            findings.extend(info.findings)
            for token, line, col, in_loop in info.cond_waits:
                if not in_loop:
                    findings.append(
                        Finding(
                            path=info.path,
                            line=line,
                            col=col,
                            rule="RT204",
                            message=(
                                f"{info.qualname} calls {token}.wait() "
                                f"outside a predicate loop — wakeups are "
                                f"spurious by spec"
                            ),
                        )
                    )
            for blk in info.blocking:
                if blk.held:
                    findings.append(
                        Finding(
                            path=info.path,
                            line=blk.line,
                            col=blk.col,
                            rule="RT203",
                            message=(
                                f"{info.qualname} calls {blk.what} while "
                                f"holding {', '.join(sorted(blk.held))} — "
                                f"move the blocking call outside the lock"
                            ),
                        )
                    )
    findings.extend(_lock_graph(model))
    findings.extend(_finalizer_findings(model))
    noqa_by_path = {pf.path: pf.noqa for pf in table.files}
    kept: List[Finding] = []
    for finding in findings:
        if only is not None and finding.rule in RULES and finding.rule not in only:
            continue
        noqa = noqa_by_path.get(finding.path, {})
        suppressed = noqa.get(finding.line)
        if finding.line in noqa and (
            suppressed is None or finding.rule in suppressed
        ):
            continue
        kept.append(finding)
    # Noqa hygiene (RT290) audits the RAW findings and bypasses
    # suppression — a stale noqa cannot suppress its own report.
    if only is None or "RT290" in only:
        for path, source in sources:
            kept.extend(
                noqa_hygiene(
                    path,
                    source,
                    findings,
                    family_digit="2",
                    known_ids=set(RULES),
                    hygiene_id="RT290",
                )
            )
    # A judgment can be reached via more than one path (lexical +
    # inherited); report each (path, line, rule) once.
    uniq: Dict[Tuple[str, int, str], Finding] = {}
    for f in kept:
        uniq.setdefault((f.path, f.line, f.rule), f)
    out = list(uniq.values())
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def _rule_filter(rules: Optional[Iterable[str]]) -> Optional[Set[str]]:
    if rules is None:
        return None
    wanted = {r.upper() for r in rules}
    unknown = wanted - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
    return wanted


def race_paths(
    paths: Sequence[str], rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    sources: List[Tuple[str, str]] = []
    findings: List[Finding] = []
    for file_path in _iter_py_files(paths):
        try:
            with open(file_path, "r", encoding="utf-8") as f:
                sources.append((file_path, f.read()))
        except (OSError, UnicodeDecodeError) as e:
            findings.append(
                Finding(
                    path=file_path,
                    line=1,
                    col=1,
                    rule="RT000",
                    message=f"unreadable: {e}",
                )
            )
    findings.extend(race_sources(sources, rules))
    return findings


def main(argv: Optional[Sequence[str]] = None, out=None) -> int:
    """CLI body shared by `ray_tpu devtools race` and `python -m
    ray_tpu.devtools.concurrency`. Exit codes mirror lint/check: 0
    clean, 1 findings, 2 usage/IO errors."""
    out = out if out is not None else sys.stdout
    parser = argparse.ArgumentParser(
        prog="ray_tpu devtools race",
        description=(
            "whole-program concurrency analyzer (rules RT201-RT206 + "
            "RT290 noqa hygiene; suppress with '# rt: noqa[RT2xx]')"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=(
            "files or directories to analyze as ONE program (default: "
            "the installed ray_tpu package)"
        ),
    )
    parser.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="emit findings as a JSON list (CI mode)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0
    if args.list_rules:
        for rule_id, title in RULES.items():
            print(f"{rule_id}  {title}", file=out)
        return 0
    if not args.paths:
        args.paths = [
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ]
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(
            f"race: no such path(s): {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2
    only = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    try:
        findings = race_paths(args.paths, only)
    except ValueError as e:
        print(f"race: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps([asdict(f) for f in findings], indent=2), file=out)
    else:
        for finding in findings:
            print(finding.render(), file=out)
        if findings:
            print(f"{len(findings)} finding(s)", file=out)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
