"""Whole-program contract checker (`ray_tpu check`, rules RT101-RT106).

Two phases (see contracts.py for phase 1): build a symbol table of
every remote function/actor signature, RPC handler, wire schema, call
site, and the shared option-key universe — then re-walk every file and
judge each call site against the contract it targets:

| id    | contract violated                                            |
|-------|--------------------------------------------------------------|
| RT101 | .remote() arity/keywords vs the decorated signature          |
|       | (tasks, actor creation, and actor methods via typed handles) |
| RT102 | unknown or invalid-typed .options()/@rt.remote(...) keys     |
|       | (same key universe the runtime validator enforces)           |
| RT103 | client.call("m") with no registered handler; handlers no     |
|       | call site ever names (dead wire surface)                     |
| RT104 | call-site kwargs inconsistent with the method's wire.SCHEMAS |
|       | entry; handlers served without any schema                    |
| RT105 | obviously unserializable .remote() arguments (locks,         |
|       | sockets, open files)                                         |
| RT106 | fire-and-forget .remote() whose ObjectRef is discarded —     |
|       | task errors can never be observed                            |

Where lint answers "is this line idiomatic", check answers "do the two
sides of this process boundary still agree". Both share the same
suppression (`# rt: noqa[RTxxx]`), output formats (`--json`), and exit
codes (0 clean / 1 findings / 2 usage errors), so CI treats them as
one gate (`ray_tpu devtools all`).

Resolution is deliberately high-precision: a receiver is only judged
when it resolves to a known symbol (module binding, import edge, or a
globally unique name) — `.options()` on a serve DeploymentHandle or
`.remote()` through an untracked alias stays silent rather than
guessing. RT103/RT104 likewise stay silent when the analyzed tree
contains no handler registry / schema table at all (checking one file
in isolation must not drown it in "unknown method" noise).
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import asdict
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .contracts import (
    RPC_VERBS,
    RemoteActor,
    RemoteFunc,
    Signature,
    SymbolTable,
    build_symbol_table,
)
from .lint import Finding, _dotted, _iter_py_files, noqa_hygiene

__all__ = ["check_sources", "check_paths", "main", "RULES"]

#: id -> one-line title (the --list-rules table).
RULES: Dict[str, str] = {
    "RT101": ".remote() arity/keyword mismatch vs decorated signature",
    "RT102": "unknown or invalid-typed .options()/@rt.remote option key",
    "RT103": "RPC method with no registered handler / dead handler",
    "RT104": "call-site kwargs drift vs wire schema / schema-less handler",
    "RT105": "obviously unserializable value passed to .remote()",
    "RT106": "fire-and-forget .remote(): result ObjectRef is discarded",
    "RT190": "stale or unknown '# rt: noqa' suppression (check family)",
}

#: Handler methods invoked by infrastructure rather than literal call
#: sites: the server synthesizes _disconnect on EOF; ping is the
#: liveness probe external tooling/tests dial directly.
INFRA_LIVE_METHODS = frozenset({"_disconnect", "ping"})

#: Constructors whose results never survive pickling across a process
#: boundary (RT105).
_UNSERIALIZABLE = {
    "threading.Thread": "thread",
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Condition": "condition",
    "threading.Event": "event",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "threading.Barrier": "barrier",
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "socket.socketpair": "socket",
    "open": "open file",
    "io.open": "open file",
}


def _fmt_types(types: Tuple[type, ...]) -> str:
    return "/".join(t.__name__ for t in types)


# ---------------------------------------------------------------------------
# per-file pass (RT101, RT102, RT105, RT106)
# ---------------------------------------------------------------------------


class _CheckVisitor(ast.NodeVisitor):
    def __init__(self, path: str, table: SymbolTable, sink: List[Finding]):
        self.path = path
        self.table = table
        self.sink = sink
        from ray_tpu._private.options import (
            ACTOR_OPTIONS,
            NUM_RETURNS_STRINGS,
            TASK_OPTIONS,
            valid_keys,
        )

        self._task_options = TASK_OPTIONS
        self._actor_options = ACTOR_OPTIONS
        self._num_returns_strings = NUM_RETURNS_STRINGS
        self._valid_keys = valid_keys
        #: Scope stack: var name -> ("handle", RemoteActor) for actor
        #: handles, or ("unser", kind) for unserializable locals, or a
        #: RemoteFunc/RemoteActor alias from `x = f` / `x = f.options()`.
        self._scopes: List[Dict[str, object]] = [{}]

    # -- plumbing ------------------------------------------------------
    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.sink.append(
            Finding(
                path=self.path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0) + 1,
                rule=rule,
                message=message,
            )
        )

    def _lookup(self, name: str):
        for scope in reversed(self._scopes):
            if name in scope:
                return scope[name]
        return self.table.resolve(self.path, name)

    # -- scopes --------------------------------------------------------
    def _visit_scope(self, node):
        self._scopes.append({})
        self.generic_visit(node)
        self._scopes.pop()

    def visit_ClassDef(self, node):
        self._bind_def(node)
        self._check_decorator_options(node)
        self._visit_scope(node)

    # -- receiver resolution -------------------------------------------
    def _resolve_target(self, expr: ast.expr):
        """Expr E of `E.remote(...)` -> ("func", sym) | ("init", sym) |
        ("method", actor, name) | None."""
        # strip .options(...) chains: options() returns the same kind.
        while (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "options"
        ):
            expr = expr.func.value
        if isinstance(expr, ast.Name):
            sym = self._lookup(expr.id)
            if isinstance(sym, RemoteFunc):
                return ("func", sym)
            if isinstance(sym, RemoteActor):
                return ("init", sym)
            if isinstance(sym, tuple) and sym[0] == "handle":
                return None  # bare handle.remote() — not a thing
            return None
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            bound = self._lookup(expr.value.id)
            if isinstance(bound, tuple) and bound[0] == "handle":
                return ("method", bound[1], expr.attr)
        return None

    # -- RT101 ---------------------------------------------------------
    def _check_arity(
        self, node: ast.Call, sig: Signature, what: str
    ) -> None:
        has_starred = any(isinstance(a, ast.Starred) for a in node.args)
        has_star_kw = any(kw.arg is None for kw in node.keywords)
        n_pos = len(node.args)
        kw_names = [kw.arg for kw in node.keywords if kw.arg is not None]
        if (
            not has_starred
            and not sig.vararg
            and n_pos > len(sig.params)
        ):
            self._emit(
                "RT101",
                node,
                f"{what}.remote() takes at most {len(sig.params)} "
                f"positional argument(s) ({n_pos} given)",
            )
        if not sig.kwarg:
            legal = sig.keyword_names()
            for name in kw_names:
                if name not in legal:
                    self._emit(
                        "RT101",
                        node,
                        f"{what}.remote() got an unexpected keyword "
                        f"argument {name!r}",
                    )
        if not has_starred:
            covered = set(sig.params[: min(n_pos, len(sig.params))])
            for name in kw_names:
                if name in covered:
                    self._emit(
                        "RT101",
                        node,
                        f"{what}.remote() got multiple values for "
                        f"argument {name!r}",
                    )
            if not has_star_kw:
                missing = [
                    p
                    for p in sig.params[n_pos : sig.required_positional]
                    if p not in kw_names
                ]
                missing += [
                    k
                    for k, has_default in sig.kwonly.items()
                    if not has_default and k not in kw_names
                ]
                if missing:
                    self._emit(
                        "RT101",
                        node,
                        f"{what}.remote() missing required "
                        f"argument(s): {', '.join(missing)}",
                    )

    # -- RT102 ---------------------------------------------------------
    def _check_option_items(
        self,
        node_for_anchor: ast.AST,
        items: Iterable[Tuple[str, ast.expr]],
        kind: str,
        what: str,
    ) -> None:
        table = (
            self._task_options if kind == "task" else self._actor_options
        )
        # Same helper the runtime error message uses: the two halves
        # of RT102 can never name different valid sets.
        valid = ", ".join(self._valid_keys(kind))
        for key, value in items:
            if key not in table:
                self._emit(
                    "RT102",
                    value if hasattr(value, "lineno") else node_for_anchor,
                    f"unknown {kind} option {key!r} on {what} — "
                    f"silently ignored at submission; valid: {valid}",
                )
                continue
            spec = table[key]
            if spec is None or not isinstance(value, ast.Constant):
                continue
            literal = value.value
            if type(literal) not in spec:
                self._emit(
                    "RT102",
                    value,
                    f"{kind} option {key!r} on {what} expects "
                    f"{_fmt_types(spec)}, got "
                    f"{type(literal).__name__} ({literal!r})",
                )
            elif (
                key == "num_returns"
                and isinstance(literal, str)
                and literal not in self._num_returns_strings
            ):
                self._emit(
                    "RT102",
                    value,
                    f"num_returns string must be one of "
                    f"{'/'.join(self._num_returns_strings)}, "
                    f"got {literal!r}",
                )

    def _bind_def(self, node) -> None:
        """Bind a decorated def's symbol into the ENCLOSING scope —
        the lexical-shadowing behavior real Python has, so the second
        `@rt.remote class A` in a file resolves to itself (not to the
        file's last A) for call sites in its own scope."""
        sym = self.table.by_def.get((self.path, node.lineno))
        if sym is not None:
            self._scopes[-1][node.name] = sym

    def _check_decorator_options(self, node) -> None:
        sym = self.table.by_def.get((self.path, node.lineno))
        if isinstance(sym, RemoteFunc):
            self._check_option_items(
                node, sym.options.items(), "task", f"@remote {sym.name}"
            )
        elif isinstance(sym, RemoteActor):
            self._check_option_items(
                node, sym.options.items(), "actor", f"@remote {sym.name}"
            )

    # -- RT105 ---------------------------------------------------------
    def _unserializable_kind(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Call):
            return _UNSERIALIZABLE.get(_dotted(expr.func))
        if isinstance(expr, ast.Name):
            bound = self._lookup(expr.id)
            if isinstance(bound, tuple) and bound[0] == "unser":
                return bound[1]
        return None

    # -- visits --------------------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        if len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            var = node.targets[0].id
            value = node.value
            # h = Actor.remote(...) / h = Actor.options(...).remote(...)
            if (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "remote"
            ):
                target = self._resolve_target(value.func.value)
                if target is not None and target[0] == "init":
                    self._scopes[-1][var] = ("handle", target[1])
            # x = f / x = f.options(...): alias keeps resolving
            elif isinstance(value, ast.Name):
                sym = self._lookup(value.id)
                if isinstance(sym, (RemoteFunc, RemoteActor)):
                    self._scopes[-1][var] = sym
            elif (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "options"
                and isinstance(value.func.value, ast.Name)
            ):
                sym = self._lookup(value.func.value.id)
                if isinstance(sym, (RemoteFunc, RemoteActor)):
                    self._scopes[-1][var] = sym
            # lock = threading.Lock() and friends
            elif isinstance(value, ast.Call):
                kind = _UNSERIALIZABLE.get(_dotted(value.func))
                if kind is not None:
                    self._scopes[-1][var] = ("unser", kind)
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr):
        # RT106: statement-level `f.remote(...)` whose ref vanishes.
        value = node.value
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr == "remote"
        ):
            target = self._resolve_target(value.func.value)
            if target is not None and target[0] in ("func", "method"):
                name = (
                    target[1].name
                    if target[0] == "func"
                    else f"{target[1].name}.{target[2]}"
                )
                self._emit(
                    "RT106",
                    value,
                    f"result ObjectRef of {name}.remote() is discarded"
                    " — task errors can never be observed; keep the "
                    "ref and get()/wait() it (or noqa a deliberate "
                    "fire-and-forget)",
                )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "remote":
                target = self._resolve_target(func.value)
                if target is not None:
                    if target[0] == "func":
                        self._check_arity(
                            node, target[1].sig, target[1].name
                        )
                    elif target[0] == "init":
                        self._check_arity(
                            node, target[1].init, target[1].name
                        )
                    elif target[0] == "method":
                        actor, mname = target[1], target[2]
                        sig = actor.methods.get(mname)
                        if sig is None:
                            # Inherited methods are invisible to the
                            # class-body scan: judge only base-less
                            # classes, where absence is definitive.
                            if not actor.has_bases:
                                self._emit(
                                    "RT101",
                                    node,
                                    f"actor {actor.name} has no method "
                                    f"{mname!r} (methods: "
                                    f"{', '.join(sorted(actor.methods)) or 'none'})",
                                )
                        else:
                            self._check_arity(
                                node, sig, f"{actor.name}.{mname}"
                            )
                # RT105 applies to ANY .remote() — a lock in flight is
                # wrong no matter how the receiver was built.
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if isinstance(arg, ast.Starred):
                        continue
                    kind = self._unserializable_kind(arg)
                    if kind is not None:
                        self._emit(
                            "RT105",
                            arg,
                            f"{kind} passed to .remote() cannot be "
                            "serialized across the process boundary; "
                            "create it inside the task/actor instead",
                        )
            elif func.attr == "options" and isinstance(
                func.value, ast.Name
            ):
                sym = self._lookup(func.value.id)
                if isinstance(sym, RemoteFunc):
                    self._check_option_items(
                        node,
                        [
                            (kw.arg, kw.value)
                            for kw in node.keywords
                            if kw.arg is not None
                        ],
                        "task",
                        sym.name,
                    )
                elif isinstance(sym, RemoteActor):
                    self._check_option_items(
                        node,
                        [
                            (kw.arg, kw.value)
                            for kw in node.keywords
                            if kw.arg is not None
                        ],
                        "actor",
                        sym.name,
                    )
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self._bind_def(node)
        self._check_decorator_options(node)
        self._visit_scope(node)

    visit_AsyncFunctionDef = visit_FunctionDef


# ---------------------------------------------------------------------------
# global pass (RT103, RT104)
# ---------------------------------------------------------------------------


def _global_findings(table: SymbolTable) -> List[Finding]:
    out: List[Finding] = []
    handlers = table.handlers
    schemas = table.schemas

    # RT103a: call site naming a method nobody registers. Silent when
    # the analyzed tree has no registry at all (partial-tree runs).
    if handlers:
        for site in table.call_sites:
            if site.method not in handlers:
                out.append(
                    Finding(
                        path=site.path,
                        line=site.lineno,
                        col=site.col,
                        rule="RT103",
                        message=(
                            f".{site.verb}({site.method!r}, ...) names "
                            "a method with no registered handler — the "
                            "server will reply 'no such method'"
                        ),
                    )
                )

    # RT103b: dead handlers — registered, but no call site or dynamic
    # string witness anywhere names them. Needs call sites to exist
    # (an isolated server file has no callers by construction).
    if handlers and table.call_sites:
        called = {site.method for site in table.call_sites}
        for method, defs in sorted(handlers.items()):
            if method in called or method in INFRA_LIVE_METHODS:
                continue
            if method in table.witnesses:
                continue  # dynamic dispatch keeps it alive
            for handler in defs:
                out.append(
                    Finding(
                        path=handler.path,
                        line=handler.lineno,
                        col=1,
                        rule="RT103",
                        message=(
                            f"handler {method!r} is registered but no "
                            "call site ever names it — dead wire "
                            "surface (remove it, or noqa if external "
                            "clients dial it)"
                        ),
                    )
                )

    if schemas:
        # RT104a: handlers served without any schema entry.
        for method, defs in sorted(handlers.items()):
            if method in schemas:
                continue
            for handler in defs:
                out.append(
                    Finding(
                        path=handler.path,
                        line=handler.lineno,
                        col=1,
                        rule="RT104",
                        message=(
                            f"handler {method!r} has no wire.SCHEMAS "
                            "entry — its arguments are never "
                            "validated; add a per-method schema"
                        ),
                    )
                )
        # RT104b: call-site kwargs vs the method's schema.
        for site in table.call_sites:
            schema = schemas.get(site.method)
            if schema is None:
                continue
            reserved = RPC_VERBS[site.verb]
            sent = site.kwargs - reserved
            unknown = sorted(sent - set(schema))
            for name in unknown:
                out.append(
                    Finding(
                        path=site.path,
                        line=site.lineno,
                        col=site.col,
                        rule="RT104",
                        message=(
                            f"kwarg {name!r} is not in the "
                            f"{site.method!r} wire schema (fields: "
                            f"{', '.join(sorted(schema)) or 'none'}) — "
                            "server-side validation will reject or "
                            "silently drop it"
                        ),
                    )
                )
            if not site.has_star_kwargs:
                missing = sorted(
                    f
                    for f, spec in schema.items()
                    if not spec.optional and f not in sent
                )
                if missing:
                    out.append(
                        Finding(
                            path=site.path,
                            line=site.lineno,
                            col=site.col,
                            rule="RT104",
                            message=(
                                f".{site.verb}({site.method!r}, ...) "
                                "omits required schema field(s): "
                                f"{', '.join(missing)}"
                            ),
                        )
                    )
    return out


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def check_sources(
    sources: Sequence[Tuple[str, str]],
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Check a set of (path, source) blobs as one program."""
    only = _rule_filter(rules)
    table = build_symbol_table(sources)
    findings: List[Finding] = []
    parsed_paths = {pf.path for pf in table.files}
    for path, source in sources:
        if path not in parsed_paths:
            try:
                ast.parse(source, filename=path)
            except SyntaxError as e:
                findings.append(
                    Finding(
                        path=path,
                        line=e.lineno or 1,
                        col=(e.offset or 0) + 1,
                        rule="RT000",
                        message=f"file does not parse: {e.msg}",
                    )
                )
    for parsed in table.files:
        _CheckVisitor(parsed.path, table, findings).visit(parsed.tree)
    findings.extend(_global_findings(table))
    noqa_by_path = {pf.path: pf.noqa for pf in table.files}
    kept: List[Finding] = []
    for finding in findings:
        if only is not None and finding.rule in RULES and finding.rule not in only:
            continue
        noqa = noqa_by_path.get(finding.path, {})
        suppressed = noqa.get(finding.line)
        if finding.line in noqa and (
            suppressed is None or finding.rule in suppressed
        ):
            continue
        kept.append(finding)
    # Noqa hygiene (RT190) audits the RAW findings and bypasses
    # suppression — a stale noqa cannot suppress its own report.
    if only is None or "RT190" in only:
        for path, source in sources:
            kept.extend(
                noqa_hygiene(
                    path,
                    source,
                    findings,
                    family_digit="1",
                    known_ids=set(RULES),
                    hygiene_id="RT190",
                )
            )
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def _rule_filter(rules: Optional[Iterable[str]]) -> Optional[Set[str]]:
    if rules is None:
        return None
    wanted = {r.upper() for r in rules}
    unknown = wanted - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
    return wanted


def check_paths(
    paths: Sequence[str], rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    sources: List[Tuple[str, str]] = []
    findings: List[Finding] = []
    for file_path in _iter_py_files(paths):
        try:
            with open(file_path, "r", encoding="utf-8") as f:
                sources.append((file_path, f.read()))
        except (OSError, UnicodeDecodeError) as e:
            findings.append(
                Finding(
                    path=file_path,
                    line=1,
                    col=1,
                    rule="RT000",
                    message=f"unreadable: {e}",
                )
            )
    findings.extend(check_sources(sources, rules))
    return findings


def main(argv: Optional[Sequence[str]] = None, out=None) -> int:
    """CLI body shared by `ray_tpu check` and `python -m
    ray_tpu.devtools.check`. Exit codes mirror lint: 0 clean, 1
    findings, 2 usage/IO errors."""
    out = out if out is not None else sys.stdout
    parser = argparse.ArgumentParser(
        prog="ray_tpu check",
        description=(
            "whole-program contract checker (rules RT101-RT106 + RT190 "
            "noqa hygiene; suppress with '# rt: noqa[RTxxx]')"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=(
            "files or directories to check as ONE program (default: "
            "the installed ray_tpu package)"
        ),
    )
    parser.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="emit findings as a JSON list (CI mode)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0
    if args.list_rules:
        for rule_id, title in RULES.items():
            print(f"{rule_id}  {title}", file=out)
        return 0
    if not args.paths:
        args.paths = [
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ]
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(
            f"check: no such path(s): {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2
    only = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    try:
        findings = check_paths(args.paths, only)
    except ValueError as e:
        print(f"check: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps([asdict(f) for f in findings], indent=2), file=out)
    else:
        for finding in findings:
            print(finding.render(), file=out)
        if findings:
            print(f"{len(findings)} finding(s)", file=out)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
