"""Distributed-correctness linter engine (`ray_tpu lint`).

Reference motivation: the reference project backs its C++ core with
sanitizer CI (SURVEY §5.2) but the *distributed* bug classes — payload
-equality dedup of retryable messages, namespace pinning, blocking
gets inside actors, nondeterminism on replayable paths — live in
Python and slip past generic linters because they are framework
idioms, not syntax errors. This module is a purpose-built AST pass
over ray_tpu's own conventions: one parse per file, one walk, every
registered rule (devtools/rules.py, RT001–RT010) dispatched from the
same visitor with shared scope context.

Suppressions: a finding is dropped when its physical line carries
``# rt: noqa`` (all rules) or ``# rt: noqa[RT002]`` /
``# rt: noqa[RT002,RT004]`` (listed rules only). Suppressions are
deliberately per-line and explicit — a wildcard file-level opt-out
would hide exactly the drift this tool exists to catch.

Output: human ``path:line:col: RTxxx message`` lines, or ``--json``
(list of finding objects) for CI. Exit codes: 0 clean, 1 findings,
2 usage/IO errors.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "LintContext",
    "lint_source",
    "lint_paths",
    "noqa_hygiene",
    "main",
]

_NOQA_RE = re.compile(
    r"#\s*rt:\s*noqa(?:\[(?P<rules>[A-Z0-9,\s]+)\])?", re.IGNORECASE
)

_RULE_ID_RE = re.compile(r"RT\d{3}")

#: Rule-family ownership for noqa hygiene: RT0xx lint, RT1xx check,
#: RT2xx race, RT3xx accel. Each pass audits only the suppressions it
#: owns; lint additionally audits ids no family owns.
_FAMILY_DIGITS = {"0": "lint", "1": "check", "2": "race", "3": "accel"}

#: The per-pass hygiene rule ids themselves — not suppressible (a
#: stale suppression must not be able to suppress its own report).
_HYGIENE_IDS = {"RT090", "RT190", "RT290", "RT390"}

#: lint's own hygiene rule (engine-level: not an AST walker rule).
HYGIENE_RULE = ("RT090", "stale or unknown '# rt: noqa' suppression")


@dataclass
class Finding:
    """One rule violation at a source location."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


def _parse_noqa(source: str) -> Dict[int, Optional[set]]:
    """line -> None (suppress all rules) or {rule ids} to suppress."""
    out: Dict[int, Optional[set]] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        if "rt:" not in text:
            continue
        match = _NOQA_RE.search(text)
        if match is None:
            continue
        rules = match.group("rules")
        if rules is None:
            out[lineno] = None
        else:
            out[lineno] = {
                r.strip().upper() for r in rules.split(",") if r.strip()
            }
    return out


def _noqa_comment_rules(source: str) -> Dict[int, Set[str]]:
    """line -> explicit rule-id set, counting only genuine COMMENT
    tokens. Unlike `_parse_noqa` (which is a per-line regex so that
    suppression stays cheap and predictable), hygiene must NOT judge
    noqa text embedded in string literals — test fixtures build
    sources containing noqa markers all the time."""
    out: Dict[int, Set[str]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            match = _NOQA_RE.search(tok.string)
            if match is None or match.group("rules") is None:
                continue
            out[tok.start[0]] = {
                r.strip().upper()
                for r in match.group("rules").split(",")
                if r.strip()
            }
    except (tokenize.TokenError, IndentationError, SyntaxError, ValueError):
        pass  # unparseable files already get RT000
    return out


def noqa_hygiene(
    path: str,
    source: str,
    raw_findings: Sequence[Finding],
    family_digit: str,
    known_ids: Set[str],
    hygiene_id: str,
    orphan_families: bool = False,
) -> List[Finding]:
    """Audit explicit ``# rt: noqa[RTxxx]`` comments against the RAW
    (pre-suppression) findings of the owning pass: an id that does not
    exist, or that never fires on its line, is itself a finding —
    stale suppressions must not rot silently. Shared by all four
    passes (lint RT090 / check RT190 / race RT290 / accel RT390);
    `orphan_families` additionally makes lint the reporter for ids no
    family owns (RT9xx typos etc.). Bare ``# rt: noqa`` is exempt: it
    names no claim to audit."""
    fired: Dict[int, Set[str]] = {}
    for finding in raw_findings:
        if finding.path == path:
            fired.setdefault(finding.line, set()).add(finding.rule)
    out: List[Finding] = []
    for line, ids in sorted(_noqa_comment_rules(source).items()):
        for rid in sorted(ids):
            if rid in _HYGIENE_IDS:
                if rid[2] == family_digit:
                    out.append(
                        Finding(
                            path=path, line=line, col=1, rule=hygiene_id,
                            message=(
                                f"'{rid}' is the noqa-hygiene rule itself "
                                f"and cannot be suppressed — remove it and "
                                f"fix the stale suppression it reports"
                            ),
                        )
                    )
                continue
            if _RULE_ID_RE.fullmatch(rid) is None:
                if orphan_families:
                    out.append(
                        Finding(
                            path=path, line=line, col=1, rule=hygiene_id,
                            message=(
                                f"noqa names malformed rule id '{rid}' "
                                f"(expected RTxyz)"
                            ),
                        )
                    )
                continue
            digit = rid[2]
            if digit == family_digit:
                if rid not in known_ids:
                    out.append(
                        Finding(
                            path=path, line=line, col=1, rule=hygiene_id,
                            message=(
                                f"noqa names unknown rule id {rid} — no "
                                f"such rule in the "
                                f"{_FAMILY_DIGITS[digit]} family"
                            ),
                        )
                    )
                elif rid not in fired.get(line, ()):
                    out.append(
                        Finding(
                            path=path, line=line, col=1, rule=hygiene_id,
                            message=(
                                f"noqa suppresses {rid}, which does not "
                                f"fire on this line — stale suppression; "
                                f"remove it"
                            ),
                        )
                    )
            elif orphan_families and digit not in _FAMILY_DIGITS:
                out.append(
                    Finding(
                        path=path, line=line, col=1, rule=hygiene_id,
                        message=(
                            f"noqa names unknown rule id {rid} — no "
                            f"devtools family owns RT{digit}xx"
                        ),
                    )
                )
    return out


class LintContext:
    """Shared walk state every rule reads instead of re-deriving.

    The stacks track syntactic position (function nesting, enclosing
    classes + whether they are actor classes); `at_import_time` is the
    fork-safety question "does this statement run when the module is
    imported" (module body and class bodies — both execute on import;
    function bodies do not)."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.func_stack: List[ast.AST] = []
        self.class_stack: List[Tuple[ast.ClassDef, bool]] = []

    # -- position helpers ---------------------------------------------
    @property
    def at_import_time(self) -> bool:
        return not self.func_stack

    @property
    def current_func(self) -> Optional[ast.AST]:
        return self.func_stack[-1] if self.func_stack else None

    @property
    def in_async_func(self) -> bool:
        return isinstance(self.current_func, ast.AsyncFunctionDef)

    @property
    def in_actor_class(self) -> bool:
        """Innermost method context belongs to an actor class: the
        class is directly decorated @remote / @rt.remote /
        @ray_tpu.remote (bare or called)."""
        if not self.class_stack:
            return False
        # Only a method defined directly on the actor class counts —
        # a nested helper class resets the context.
        return self.class_stack[-1][1]


def _dotted(node: ast.AST) -> str:
    """`a.b.c` -> "a.b.c"; bare names -> "name"; else ""."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_remote_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    name = _dotted(dec)
    return name in ("remote", "rt.remote", "ray_tpu.remote") or (
        name.endswith(".remote") and name.count(".") == 1
    )


class _Walker(ast.NodeVisitor):
    """Single-pass dispatcher: maintains the LintContext stacks and
    hands every node to each in-scope rule."""

    def __init__(self, ctx: LintContext, rules: Sequence, sink: List[Finding]):
        self.ctx = ctx
        self.rules = rules
        self.sink = sink

    def _emit(self, rule, node: ast.AST, message: str) -> None:
        self.sink.append(
            Finding(
                path=self.ctx.path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0) + 1,
                rule=rule.id,
                message=message,
            )
        )

    def _dispatch(self, hook: str, node: ast.AST) -> None:
        for rule in self.rules:
            fn = getattr(rule, hook, None)
            if fn is None:
                continue
            for message, anchor in fn(node, self.ctx) or ():
                self._emit(rule, anchor if anchor is not None else node, message)

    # -- scope-tracking visits ----------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        is_actor = any(_is_remote_decorator(d) for d in node.decorator_list)
        self.ctx.class_stack.append((node, is_actor))
        self.generic_visit(node)
        self.ctx.class_stack.pop()

    def _visit_func(self, node) -> None:
        self._dispatch("on_functiondef", node)
        self.ctx.func_stack.append(node)
        self.generic_visit(node)
        self.ctx.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- node-type hooks ----------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        self._dispatch("on_call", node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        self._dispatch("on_compare", node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        self._dispatch("on_except", node)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._dispatch("on_assign", node)
        self.generic_visit(node)

    def visit_keyword(self, node: ast.keyword) -> None:
        self._dispatch("on_keyword", node)
        self.generic_visit(node)


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def _rules_for(path: str, rules: Sequence) -> List:
    norm = _norm(path)
    return [r for r in rules if r.in_scope(norm)]


def _wanted_ids(only: Optional[Iterable[str]]) -> Optional[Set[str]]:
    if only is None:
        return None
    from .rules import ALL_RULES

    wanted = {r.upper() for r in only}
    unknown = wanted - ({r.id for r in ALL_RULES} | {HYGIENE_RULE[0]})
    if unknown:
        raise ValueError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
    return wanted


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint one source blob; `path` drives per-rule scoping."""
    wanted = _wanted_ids(rules)
    from .rules import ALL_RULES

    # Always walk with every in-scope rule: noqa hygiene judges the
    # RAW findings, so staleness cannot depend on the --rules filter.
    active = _rules_for(path, list(ALL_RULES))
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                path=path,
                line=e.lineno or 1,
                col=(e.offset or 0) + 1,
                rule="RT000",
                message=f"file does not parse: {e.msg}",
            )
        ]
    ctx = LintContext(path, tree)
    sink: List[Finding] = []
    if active:
        _Walker(ctx, active, sink).visit(tree)
    noqa = _parse_noqa(source)
    kept = []
    for finding in sink:
        if wanted is not None and finding.rule not in wanted:
            continue
        suppressed = noqa.get(finding.line)
        if finding.line in noqa and (
            suppressed is None or finding.rule in suppressed
        ):
            continue
        kept.append(finding)
    if wanted is None or HYGIENE_RULE[0] in wanted:
        known = {r.id for r in ALL_RULES} | {"RT000"}
        kept.extend(
            noqa_hygiene(
                path,
                source,
                sink,
                family_digit="0",
                known_ids=known,
                hygiene_id=HYGIENE_RULE[0],
                orphan_families=True,
            )
        )
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [
                d
                for d in sorted(dirnames)
                if not d.startswith(".") and d != "__pycache__"
            ]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def lint_paths(
    paths: Sequence[str], rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    findings: List[Finding] = []
    for file_path in _iter_py_files(paths):
        try:
            with open(file_path, "r", encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(
                Finding(
                    path=file_path,
                    line=1,
                    col=1,
                    rule="RT000",
                    message=f"unreadable: {e}",
                )
            )
            continue
        findings.extend(lint_source(source, file_path, rules))
    return findings


def main(argv: Optional[Sequence[str]] = None, out=None) -> int:
    """CLI body shared by `ray_tpu lint` and `python -m
    ray_tpu.devtools.lint`. Returns the exit code (0 clean, 1
    findings, 2 errors) instead of exiting, so tests and the CLI
    wrapper both drive it directly."""
    out = out if out is not None else sys.stdout
    parser = argparse.ArgumentParser(
        prog="ray_tpu lint",
        description=(
            "framework-aware distributed-correctness linter "
            "(rules RT001-RT010 + RT090 noqa hygiene; suppress with "
            "'# rt: noqa[RTxxx]')"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=(
            "files or directories to lint (default: the installed "
            "ray_tpu package, wherever the CLI runs from)"
        ),
    )
    parser.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="emit findings as a JSON list (CI mode)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0
    from .rules import ALL_RULES

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.title}", file=out)
        print(f"{HYGIENE_RULE[0]}  {HYGIENE_RULE[1]}", file=out)
        return 0
    if not args.paths:
        # Default to the package this CLI shipped in — NOT a
        # cwd-relative "ray_tpu", which would lint nothing (or the
        # wrong tree) from any other directory.
        args.paths = [
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ]
    only = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    try:
        findings = lint_paths(args.paths, only)
    except ValueError as e:
        print(f"lint: {e}", file=sys.stderr)
        return 2
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(
            f"lint: no such path(s): {', '.join(missing)}", file=sys.stderr
        )
        return 2
    if args.as_json:
        print(json.dumps([asdict(f) for f in findings], indent=2), file=out)
    else:
        for finding in findings:
            print(finding.render(), file=out)
        if findings:
            print(f"{len(findings)} finding(s)", file=out)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
