"""Replica actor body.

Reference: python/ray/serve/_private/replica.py:750,998 — a replica
wraps the user callable; requests arrive as (method, args, kwargs);
handle-typed init args are materialized into live DeploymentHandles so
composed models call downstream deployments through the router.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any


class HandleRef:
    """Placeholder for a DeploymentHandle in pickled init args."""

    def __init__(self, app_name: str, deployment_name: str):
        self.app_name = app_name
        self.deployment_name = deployment_name


class Replica:
    def __init__(
        self,
        cls,
        init_args: tuple,
        init_kwargs: dict,
        replica_id: str,
        app_name: str = "",
        deployment_name: str = "",
    ):
        from .router import DeploymentHandle

        def materialize(value: Any) -> Any:
            if isinstance(value, HandleRef):
                return DeploymentHandle(
                    value.app_name, value.deployment_name
                )
            return value

        args = tuple(materialize(a) for a in init_args)
        kwargs = {k: materialize(v) for k, v in init_kwargs.items()}
        self._instance = cls(*args, **kwargs)
        # Multiplex LRU changes report this replica's loaded model set
        # to the controller, which long-poll-pushes it to routers
        # (multiplex.py reads this hook when it lazily builds the
        # wrapper on the first get_model call — after __init__, so
        # installing it here is early enough).
        if app_name and deployment_name:
            def _report_models(model_ids, _self=self):
                try:
                    import ray_tpu as rt

                    from .controller import CONTROLLER_NAME

                    # get_actor directly: replicas run inside worker
                    # processes where api._rt()'s driver-style init
                    # path doesn't apply.
                    controller = rt.get_actor(
                        CONTROLLER_NAME, namespace="serve"
                    )
                    controller.record_multiplexed.remote(
                        app_name,
                        deployment_name,
                        replica_id,
                        list(model_ids),
                    )
                except Exception:
                    pass

            try:
                self._instance.__serve_multiplex_report__ = (
                    _report_models
                )
            except Exception:
                pass  # __slots__ classes: no router warmth hints
        self.replica_id = replica_id
        self._served = 0
        # Replicas run with max_concurrency > 1 (controller wires
        # max_ongoing_requests through actor concurrency), so replica
        # bookkeeping must be thread-safe; the USER instance is
        # responsible for its own state under concurrent methods, as
        # in the reference's async replicas.
        self._served_lock = threading.Lock()
        self._started = time.time()

    def handle_request(
        self, method: str, args: tuple, kwargs: dict, model_id: str = ""
    ):
        from .multiplex import _set_request_model_id

        with self._served_lock:
            self._served += 1
        target = (
            self._instance
            if method == "__call__"
            else getattr(self._instance, method)
        )
        token = _set_request_model_id(model_id)
        try:
            return target(*args, **kwargs)
        finally:
            from .multiplex import _model_id_ctx

            _model_id_ctx.reset(token)

    def handle_request_streaming(
        self, method: str, args: tuple, kwargs: dict, model_id: str = ""
    ):
        """Generator variant: the user method must yield chunks; each
        yield ships to the caller immediately over the runtime's
        streaming-generator transport (reference: replica.py
        handle_request_streaming + StreamingObjectRefGenerator).
        Called with num_returns='streaming' by the router."""
        from .multiplex import _model_id_ctx, _set_request_model_id

        with self._served_lock:
            self._served += 1
        target = (
            self._instance
            if method == "__call__"
            else getattr(self._instance, method)
        )
        token = _set_request_model_id(model_id)
        try:
            yield from target(*args, **kwargs)
        finally:
            _model_id_ctx.reset(token)

    def node_id(self) -> str:
        """This replica's node (routers prefer local replicas)."""
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    def handle_batch(self, method: str, batched_args: list):
        """One call carrying many requests; the user method receives
        the list (reference: serve/batching.py _BatchQueue)."""
        with self._served_lock:
            self._served += len(batched_args)
        target = getattr(self._instance, method)
        return target([a[0] if len(a) == 1 else a for a in batched_args])

    def stats(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "pid": os.getpid(),
            "served": self._served,
            "uptime_s": time.time() - self._started,
        }

    def reconfigure(self, user_config: Any) -> None:
        if hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)

    def ping(self) -> bool:
        return True
