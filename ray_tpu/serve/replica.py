"""Replica actor body.

Reference: python/ray/serve/_private/replica.py:750,998 — a replica
wraps the user callable; requests arrive as (method, args, kwargs);
handle-typed init args are materialized into live DeploymentHandles so
composed models call downstream deployments through the router.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any


class HandleRef:
    """Placeholder for a DeploymentHandle in pickled init args."""

    def __init__(self, app_name: str, deployment_name: str):
        self.app_name = app_name
        self.deployment_name = deployment_name


class Replica:
    def __init__(
        self,
        cls,
        init_args: tuple,
        init_kwargs: dict,
        replica_id: str,
        app_name: str = "",
        deployment_name: str = "",
    ):
        from .router import DeploymentHandle

        def materialize(value: Any) -> Any:
            if isinstance(value, HandleRef):
                return DeploymentHandle(
                    value.app_name, value.deployment_name
                )
            return value

        args = tuple(materialize(a) for a in init_args)
        kwargs = {k: materialize(v) for k, v in init_kwargs.items()}
        self._instance = cls(*args, **kwargs)
        # Multiplex LRU changes report this replica's loaded model set
        # to the controller, which long-poll-pushes it to routers
        # (multiplex.py reads this hook when it lazily builds the
        # wrapper on the first get_model call — after __init__, so
        # installing it here is early enough).
        if app_name and deployment_name:
            def _report_models(model_ids, _self=self):
                try:
                    import ray_tpu as rt

                    from .controller import CONTROLLER_NAME

                    # get_actor directly: replicas run inside worker
                    # processes where api._rt()'s driver-style init
                    # path doesn't apply.
                    controller = rt.get_actor(
                        CONTROLLER_NAME, namespace="serve"
                    )
                    controller.record_multiplexed.remote(
                        app_name,
                        deployment_name,
                        replica_id,
                        list(model_ids),
                    )
                except Exception:
                    pass

            try:
                self._instance.__serve_multiplex_report__ = (
                    _report_models
                )
            except Exception:
                pass  # __slots__ classes: no router warmth hints
        self.replica_id = replica_id
        self._app_name = app_name
        self._deployment_name = deployment_name
        self._served = 0
        self._executing = 0
        # Replicas run with max_concurrency > 1 (controller wires
        # max_ongoing_requests through actor concurrency), so replica
        # bookkeeping must be thread-safe; the USER instance is
        # responsible for its own state under concurrent methods, as
        # in the reference's async replicas.
        self._served_lock = threading.Lock()
        self._started = time.time()

    def _begin_request(self, ctx: dict) -> None:
        """Request-entry bookkeeping: queue wait (router send -> here)
        and the executing gauge routers/`/api/serve` subtract from
        in-flight to derive queue depth."""
        from .observability import (
            observe_queue_wait,
            replica_executing,
        )

        with self._served_lock:
            self._served += 1
            self._executing += 1
            executing = self._executing
        sent = ctx.get("sent_ts")
        if sent is not None:
            observe_queue_wait(
                self._app_name,
                self._deployment_name,
                (time.time() - float(sent)) * 1e3,
            )
        replica_executing(
            self._app_name,
            self._deployment_name,
            self.replica_id,
            executing,
        )

    def _end_request(self) -> None:
        from .observability import replica_executing

        with self._served_lock:
            self._executing = max(0, self._executing - 1)
            executing = self._executing
        replica_executing(
            self._app_name,
            self._deployment_name,
            self.replica_id,
            executing,
        )

    def handle_request(
        self,
        method: str,
        args: tuple,
        kwargs: dict,
        model_id: str = "",
        ctx: dict = None,
    ):
        from ..util.tracing import remote_parent, span

        from .multiplex import _model_id_ctx, _set_request_model_id
        from .observability import (
            observe_handler,
            request_context,
            reset_request_context,
        )

        ctx = ctx or {}
        self._begin_request(ctx)
        target = (
            self._instance
            if method == "__call__"
            else getattr(self._instance, method)
        )
        token = _set_request_model_id(model_id)
        ctx_token = request_context(ctx)
        request_id = str(ctx.get("request_id", ""))
        t0 = time.perf_counter()
        error = False
        try:
            with remote_parent(ctx.get("trace")):
                with span(
                    "serve.handle",
                    request_id=request_id,
                    deployment=(
                        f"{self._app_name}/{self._deployment_name}"
                    ),
                ):
                    return target(*args, **kwargs)
        except BaseException:
            error = True
            raise
        finally:
            observe_handler(
                self._app_name,
                self._deployment_name,
                method,
                (time.perf_counter() - t0) * 1e3,
                error,
                request_id=request_id,
            )
            self._end_request()
            reset_request_context(ctx_token)
            _model_id_ctx.reset(token)

    def handle_request_streaming(
        self,
        method: str,
        args: tuple,
        kwargs: dict,
        model_id: str = "",
        ctx: dict = None,
    ):
        """Generator variant: the user method must yield chunks; each
        yield ships to the caller immediately over the runtime's
        streaming-generator transport (reference: replica.py
        handle_request_streaming + StreamingObjectRefGenerator).
        Called with num_returns='streaming' by the router. Latency is
        recorded over the WHOLE stream (first yield to exhaustion) —
        the number a token-streaming client experiences."""
        from .multiplex import _model_id_ctx, _set_request_model_id
        from .observability import (
            observe_handler,
            request_context,
            reset_request_context,
        )

        ctx = ctx or {}
        self._begin_request(ctx)
        target = (
            self._instance
            if method == "__call__"
            else getattr(self._instance, method)
        )
        token = _set_request_model_id(model_id)
        ctx_token = request_context(ctx)
        request_id = str(ctx.get("request_id", ""))
        t0 = time.perf_counter()
        error = False
        try:
            yield from target(*args, **kwargs)
        except BaseException:
            error = True
            raise
        finally:
            observe_handler(
                self._app_name,
                self._deployment_name,
                method,
                (time.perf_counter() - t0) * 1e3,
                error,
                request_id=request_id,
            )
            self._end_request()
            reset_request_context(ctx_token)
            _model_id_ctx.reset(token)

    def cancel_stream(self, request_id: str) -> bool:
        """Best-effort cancel of an in-flight streaming request: the
        consumer abandoned the stream (client disconnect,
        DeploymentResponseGenerator.close()), so a user callable that
        can stop producing should (the LLM engine frees the request's
        KV slot mid-decode). Instances opt in by implementing
        ``__serve_cancel_stream__(request_id) -> bool``; without the
        hook the stream simply runs to completion as before."""
        hook = getattr(
            self._instance, "__serve_cancel_stream__", None
        )
        if not callable(hook):
            return False
        try:
            return bool(hook(request_id))
        except Exception:
            return False

    def node_id(self) -> str:
        """This replica's node (routers prefer local replicas)."""
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    def handle_batch(
        self, method: str, batched_args: list, ctx: dict = None
    ):
        """One call carrying many requests; the user method receives
        the list (reference: serve/batching.py _BatchQueue). The whole
        batch shares one request context; per-item latency is the
        batch's (that is what each caller experienced)."""
        from .observability import (
            observe_handler,
            request_context,
            reset_request_context,
        )

        ctx = ctx or {}
        self._begin_request(ctx)
        with self._served_lock:
            self._served += len(batched_args) - 1
        target = getattr(self._instance, method)
        ctx_token = request_context(ctx)
        t0 = time.perf_counter()
        error = False
        try:
            return target(
                [a[0] if len(a) == 1 else a for a in batched_args]
            )
        except BaseException:
            error = True
            raise
        finally:
            dur_ms = (time.perf_counter() - t0) * 1e3
            for _ in batched_args:
                observe_handler(
                    self._app_name,
                    self._deployment_name,
                    method,
                    dur_ms,
                    error,
                    request_id=str(ctx.get("request_id", "")),
                )
            self._end_request()
            reset_request_context(ctx_token)

    def stats(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "pid": os.getpid(),
            "served": self._served,
            "executing": self._executing,
            "uptime_s": time.time() - self._started,
        }

    def reconfigure(self, user_config: Any) -> None:
        if hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)

    def ping(self) -> bool:
        return True
