"""Serve request-path observability.

Reference: python/ray/serve/_private/metrics_utils.py + the request
context module — every request carries an id, and the proxy, router,
replica and multiplex layers each record their segment of its life
into per-deployment histograms:

  serve_http_request_latency_ms   proxy: end-to-end HTTP time
  serve_router_routing_ms         handle: replica selection time
  serve_queue_wait_ms             replica: send -> execution start
  serve_request_latency_ms        replica: handler execution time
  serve_model_load_ms             multiplex: model swap (load) time
  serve_requests_total            replica: completions by outcome
  serve_http_requests_total       proxy: completions by status class
  serve_replica_executing         replica: currently-executing gauge

All ride the existing metrics pipe (util/metrics) to the head, so
they show up in `metrics_summary()`, the Prometheus endpoint and the
time-series ring with {app, deployment, ...} labels; the flight
recorder additionally keeps the most recent requests per process
(kinds ``serve.http`` / ``serve.handle`` / ``serve.model_load``)
with the request id, so `ray_tpu doctor`'s ring digests show serve
traffic next to RPC traffic.

Request ids: the proxy honors an incoming ``x-request-id`` header or
mints one; bare handle calls mint one per request. The id propagates
proxy -> router -> replica -> multiplex via the request-context dict
the router ships with every replica call, and comes back to HTTP
callers as the ``x-request-id`` response header.

Kill switch: ``RT_serve_request_metrics_enabled=0`` disables every
histogram/counter observation on this process (the request-path
analog of ``RT_flight_recorder_enabled``); request ids still
propagate — they cost one uuid per request and make error logs
correlatable even with metrics off.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from typing import Dict, Optional

__all__ = [
    "REQUEST_ID_HEADER",
    "new_request_id",
    "new_request_context",
    "request_context",
    "current_request_context",
    "get_request_id",
    "reset_request_context",
    "observe_http",
    "observe_routing",
    "observe_queue_wait",
    "observe_handler",
    "observe_model_load",
    "replica_executing",
    "observe_engine_step",
    "observe_engine_prefill",
    "observe_engine_prefix",
    "observe_engine_ttft",
    "observe_engine_finish",
    "observe_engine_weights",
    "observe_engine_policy",
    "deployment_snapshot",
]

REQUEST_ID_HEADER = "x-request-id"

#: Latency bucket boundaries (ms) shared by every serve histogram:
#: sub-ms RPC floors through multi-second model loads.
LATENCY_BUCKETS_MS = (
    1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


def _enabled() -> bool:
    raw = os.environ.get("RT_serve_request_metrics_enabled", "1")
    return raw.lower() in ("1", "true", "yes")


_ENABLED = _enabled()

#: Replica-side context of the request being handled; multiplex reads
#: it for model-load attribution, user code may read the request id.
_request_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_context", default=None
)


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def new_request_context(
    app: str,
    deployment: str,
    request_id: Optional[str] = None,
    trace: Optional[dict] = None,
) -> dict:
    """The dict the router ships with every replica call: identity +
    the send timestamp the replica turns into queue wait."""
    ctx = {
        "request_id": request_id or new_request_id(),
        "app": app,
        "deployment": deployment,
        "sent_ts": time.time(),
    }
    if trace:
        ctx["trace"] = trace
    return ctx


def request_context(ctx: Optional[dict]):
    """Set the replica-side request context; returns the reset
    token."""
    return _request_ctx.set(ctx)


def reset_request_context(token) -> None:
    _request_ctx.reset(token)


def current_request_context() -> Optional[dict]:
    """Context of the request being handled (replica-side); None
    outside a serve request."""
    return _request_ctx.get()


def get_request_id() -> str:
    """Id of the serve request being handled (usable in user handler
    code for log correlation); "" outside a serve request."""
    ctx = _request_ctx.get()
    return str(ctx.get("request_id", "")) if ctx else ""


# ---------------------------------------------------------------------
# lazy metric singletons (one instance per (name) per process; the
# metrics pipe batches records, so per-request cost is a tuple append)
# ---------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: Dict[str, object] = {}


def _histogram(
    name: str, description: str, tag_keys: tuple, boundaries=None
):
    from ..util.metrics import Histogram

    with _metrics_lock:
        metric = _metrics.get(name)
        if metric is None:
            metric = _metrics[name] = Histogram(
                name,
                description=description,
                boundaries=boundaries or LATENCY_BUCKETS_MS,
                tag_keys=tag_keys,
            )
    return metric


def _counter(name: str, description: str, tag_keys: tuple):
    from ..util.metrics import Counter

    with _metrics_lock:
        metric = _metrics.get(name)
        if metric is None:
            metric = _metrics[name] = Counter(
                name, description=description, tag_keys=tag_keys
            )
    return metric


def _gauge(name: str, description: str, tag_keys: tuple):
    from ..util.metrics import Gauge

    with _metrics_lock:
        metric = _metrics.get(name)
        if metric is None:
            metric = _metrics[name] = Gauge(
                name, description=description, tag_keys=tag_keys
            )
    return metric


def _fr(kind: str, name: str, dur_ms: float, extra: dict) -> None:
    from .._private.flight_recorder import record

    record(kind, name, dur_ms, extra)


# ---------------------------------------------------------------------
# observation hooks (each guarded: observability must never fail a
# request)
# ---------------------------------------------------------------------

def observe_http(
    app: str,
    deployment: str,
    route: str,
    status: int,
    dur_ms: float,
    request_id: str,
) -> None:
    """Proxy: one completed HTTP request (end-to-end, queueing and
    streaming included)."""
    if not _ENABLED:
        return
    try:
        tags = {"app": app, "deployment": deployment}
        _histogram(
            "serve_http_request_latency_ms",
            "End-to-end HTTP request latency at the ingress proxy",
            ("app", "deployment"),
        ).observe(dur_ms, tags=tags)
        _counter(
            "serve_http_requests_total",
            "HTTP requests completed at the ingress proxy",
            ("app", "deployment", "status"),
        ).inc(1.0, tags={**tags, "status": f"{int(status) // 100}xx"})
        _fr(
            "serve.http",
            f"{app}/{deployment}{route}",
            dur_ms,
            {
                "request_id": request_id,
                "status": int(status),
                "error": int(status) >= 500,
            },
        )
    except Exception:
        pass


def observe_routing(app: str, deployment: str, dur_ms: float) -> None:
    """Handle: time spent choosing a replica (includes any wait for
    replica membership to appear)."""
    if not _ENABLED:
        return
    try:
        _histogram(
            "serve_router_routing_ms",
            "Replica selection time in the router",
            ("app", "deployment"),
        ).observe(dur_ms, tags={"app": app, "deployment": deployment})
    except Exception:
        pass


def observe_queue_wait(
    app: str, deployment: str, dur_ms: float
) -> None:
    """Replica: router send -> handler start (actor mailbox + wire
    time; cross-host clock skew makes this approximate off-box)."""
    if not _ENABLED:
        return
    try:
        _histogram(
            "serve_queue_wait_ms",
            "Router-send to handler-start wait per request",
            ("app", "deployment"),
        ).observe(
            max(0.0, dur_ms),
            tags={"app": app, "deployment": deployment},
        )
    except Exception:
        pass


def observe_handler(
    app: str,
    deployment: str,
    method: str,
    dur_ms: float,
    error: bool,
    request_id: str = "",
) -> None:
    """Replica: handler execution time + outcome counter."""
    if not _ENABLED:
        return
    try:
        tags = {"app": app, "deployment": deployment}
        _histogram(
            "serve_request_latency_ms",
            "Handler execution latency per deployment",
            ("app", "deployment"),
        ).observe(dur_ms, tags=tags)
        _counter(
            "serve_requests_total",
            "Requests completed by replicas, by outcome",
            ("app", "deployment", "method", "outcome"),
        ).inc(
            1.0,
            tags={
                **tags,
                "method": method,
                "outcome": "error" if error else "ok",
            },
        )
        _fr(
            "serve.handle",
            f"{app}/{deployment}.{method}",
            dur_ms,
            {"request_id": request_id, "error": bool(error)},
        )
    except Exception:
        pass


def observe_model_load(model_id: str, dur_ms: float) -> None:
    """Multiplex: one model load (LRU miss). Deployment attribution
    comes from the request context the replica set around the call;
    loads outside any request (warmup) land under app=""/deployment=""
    rather than being dropped."""
    if not _ENABLED:
        return
    try:
        ctx = current_request_context() or {}
        app = str(ctx.get("app", ""))
        deployment = str(ctx.get("deployment", ""))
        _histogram(
            "serve_model_load_ms",
            "Multiplexed model load (swap) time per deployment",
            ("app", "deployment"),
        ).observe(dur_ms, tags={"app": app, "deployment": deployment})
        _fr(
            "serve.model_load",
            model_id,
            dur_ms,
            {
                "request_id": str(ctx.get("request_id", "")),
                "deployment": f"{app}/{deployment}",
            },
        )
    except Exception:
        pass


#: Throttle for the executing gauge: one gauge record per replica per
#: change is fine at test scale but pure overhead at bench rates —
#: zero-crossing edges (0 <-> nonzero, both directions) ALWAYS push;
#: same-sign updates are limited to one per period.
_GAUGE_MIN_INTERVAL_S = 0.1
_gauge_last: Dict[tuple, tuple] = {}  # key -> (ts, value)


def replica_executing(
    app: str, deployment: str, replica_id: str, executing: int
) -> None:
    """Replica: currently-executing request count. Tagged by replica
    so concurrent replicas of one deployment don't overwrite each
    other's gauge — consumers sum across the replica label."""
    if not _ENABLED:
        return
    try:
        key = (app, deployment, replica_id)
        now = time.monotonic()
        last_ts, last_value = _gauge_last.get(key, (0.0, -1))
        edge = (executing == 0) != (last_value == 0)
        if not edge and now - last_ts < _GAUGE_MIN_INTERVAL_S:
            return
        _gauge_last[key] = (now, executing)
        _gauge(
            "serve_replica_executing",
            "Requests currently executing on a replica",
            ("app", "deployment", "replica"),
        ).set(
            float(executing),
            tags={
                "app": app,
                "deployment": deployment,
                "replica": replica_id,
            },
        )
    except Exception:
        pass


# ---------------------------------------------------------------------
# continuous-batching engine (ray_tpu/llm): per-iteration decode and
# prefill timing, slot occupancy, token throughput. Tagged by model
# FAMILY on top of app/deployment — one engine per multiplexed family,
# so family series are the per-family slot accounting. Names ride the
# normal metrics pipe: labeled series on /metrics, folded per
# deployment into /api/serve by deployment_snapshot below.
# ---------------------------------------------------------------------

ENGINE_TAGS = ("app", "deployment", "family")

#: Decode-batch-size bucket boundaries (requests per step).
BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def _engine_histogram(name: str, description: str, boundaries=None):
    return _histogram(
        name, description, ENGINE_TAGS, boundaries=boundaries
    )


def observe_engine_step(
    tags: Dict[str, str],
    step_ms: float,
    batch: int,
    tokens: int,
    slots_used: int,
    slots_total: int,
    waiting: int,
    kv_used: Optional[int] = None,
    kv_total: Optional[int] = None,
    kv_cached: Optional[int] = None,
) -> None:
    """Engine: one decode iteration over the slot batch."""
    if not _ENABLED:
        return
    try:
        _engine_histogram(
            "serve_engine_decode_step_ms",
            "One decode step over the engine's slot batch",
        ).observe(step_ms, tags=tags)
        _engine_histogram(
            "serve_engine_step_batch",
            "Sequences decoded per engine step (batch size)",
            boundaries=BATCH_BUCKETS,
        ).observe(float(batch), tags=tags)
        if tokens:
            _counter(
                "serve_engine_tokens_total",
                "Tokens sampled by the engine's decode loop",
                ENGINE_TAGS,
            ).inc(float(tokens), tags=tags)
        _engine_gauges(
            tags, slots_used, slots_total, waiting,
            kv_used, kv_total, kv_cached,
        )
    except Exception:
        pass


def observe_engine_prefill(
    tags: Dict[str, str], chunk_ms: float, tokens: int
) -> None:
    """Engine: one prefill chunk (interleaved with decode steps)."""
    if not _ENABLED:
        return
    try:
        _engine_histogram(
            "serve_engine_prefill_chunk_ms",
            "One prefill chunk forward in the engine",
        ).observe(chunk_ms, tags=tags)
        _counter(
            "serve_engine_prefill_tokens_total",
            "Prompt tokens prefilled by the engine",
            ENGINE_TAGS,
        ).inc(float(tokens), tags=tags)
    except Exception:
        pass


def observe_engine_prefix(
    tags: Dict[str, str], skip_tokens: int
) -> None:
    """Engine: one admission's prefix-cache outcome. A HIT means the
    request skipped `skip_tokens` of prefill by pinning pooled blocks
    (hit-rate = hits / (hits + misses) over the counters)."""
    if not _ENABLED:
        return
    try:
        name = (
            "serve_engine_prefix_hits_total"
            if skip_tokens
            else "serve_engine_prefix_misses_total"
        )
        _counter(
            name,
            "Engine admissions whose prompt prefix "
            + ("hit" if skip_tokens else "missed")
            + " the paged KV prefix cache",
            ENGINE_TAGS,
        ).inc(1.0, tags=tags)
        if skip_tokens:
            _counter(
                "serve_engine_prefix_tokens_saved_total",
                "Prompt tokens whose prefill was skipped via "
                "prefix-cache hits",
                ENGINE_TAGS,
            ).inc(float(skip_tokens), tags=tags)
    except Exception:
        pass


def observe_engine_ttft(tags: Dict[str, str], ttft_ms: float) -> None:
    """Engine: submit -> first sampled token for one request."""
    if not _ENABLED:
        return
    try:
        _engine_histogram(
            "serve_engine_ttft_ms",
            "Engine-side time to first token per request",
        ).observe(ttft_ms, tags=tags)
    except Exception:
        pass


def observe_engine_finish(tags: Dict[str, str], reason: str) -> None:
    """Engine: one request retired (stop/length/cancelled)."""
    if not _ENABLED:
        return
    try:
        _counter(
            "serve_engine_requests_total",
            "Requests retired by the engine, by outcome",
            ENGINE_TAGS + ("outcome",),
        ).inc(1.0, tags={**tags, "outcome": reason})
    except Exception:
        pass


def observe_engine_weights(
    tags: Dict[str, str], version: int
) -> None:
    """Engine: a drainless weight push installed a new generation —
    the version now served to NEW admissions and policy batches
    (in-flight streams finish on the generation they pinned). The RL
    dataflow pairs this with `rl_weight_version`/`rl_weight_lag` from
    the learner side; the acceptance surface for weight-sync
    visibility on /metrics."""
    if not _ENABLED:
        return
    try:
        _gauge(
            "serve_engine_weight_version",
            "Weight version served to new engine admissions",
            ENGINE_TAGS,
        ).set(float(version), tags=tags)
        _counter(
            "serve_engine_weight_updates_total",
            "Drainless weight pushes installed by the engine",
            ENGINE_TAGS,
        ).inc(1.0, tags=tags)
    except Exception:
        pass


def observe_engine_policy(
    tags: Dict[str, str], batch_ms: float, rows: int, bucket: int
) -> None:
    """Engine: one policy-path batched forward (the non-LLM batch
    program serving RL action requests)."""
    if not _ENABLED:
        return
    try:
        _engine_histogram(
            "serve_engine_policy_batch_ms",
            "One policy batch-program forward in the engine",
        ).observe(batch_ms, tags=tags)
        _engine_histogram(
            "serve_engine_policy_batch_rows",
            "Rows served per policy batch (before bucket padding)",
            boundaries=BATCH_BUCKETS,
        ).observe(float(rows), tags=tags)
        _counter(
            "serve_engine_policy_rows_total",
            "Policy-path rows served by the engine",
            ENGINE_TAGS,
        ).inc(float(rows), tags=tags)
    except Exception:
        pass


def observe_engine_occupancy(
    tags: Dict[str, str],
    slots_used: int,
    slots_total: int,
    waiting: int,
    kv_used: Optional[int] = None,
    kv_total: Optional[int] = None,
    kv_cached: Optional[int] = None,
) -> None:
    """Engine: occupancy push OUTSIDE the decode step — cancellation,
    request retirement, and engine unload all free slots (and unpin
    KV blocks) without a following step, and the gauges must not
    report phantom occupancy until the next request arrives."""
    if not _ENABLED:
        return
    try:
        _engine_gauges(
            tags, slots_used, slots_total, waiting,
            kv_used, kv_total, kv_cached,
        )
    except Exception:
        pass


def _engine_gauges(
    tags: Dict[str, str],
    slots_used: int,
    slots_total: int,
    waiting: int,
    kv_used: Optional[int] = None,
    kv_total: Optional[int] = None,
    kv_cached: Optional[int] = None,
) -> None:
    """Slot-occupancy + KV-block gauges, throttled like
    replica_executing: zero-crossing edges always push, same-sign
    updates at most one per period per engine."""
    key = ("engine", tags.get("app", ""), tags.get("deployment", ""),
           tags.get("family", ""))
    now = time.monotonic()
    last_ts, last_value = _gauge_last.get(key, (0.0, -1))
    edge = (slots_used == 0) != (last_value == 0)
    if not edge and now - last_ts < _GAUGE_MIN_INTERVAL_S:
        return
    _gauge_last[key] = (now, slots_used)
    series = [
        (
            "serve_engine_slots_used",
            "KV slots occupied by decoding sequences",
            slots_used,
        ),
        (
            "serve_engine_slots_total",
            "KV slots provisioned in the engine",
            slots_total,
        ),
        (
            "serve_engine_waiting",
            "Requests queued for a free engine slot",
            waiting,
        ),
    ]
    if kv_used is not None:
        series.append((
            "serve_engine_kv_blocks_used",
            "Paged-KV blocks pinned by live requests",
            kv_used,
        ))
    if kv_total is not None:
        series.append((
            "serve_engine_kv_blocks_total",
            "Paged-KV blocks provisioned in the engine's pool",
            kv_total,
        ))
    if kv_cached is not None:
        series.append((
            "serve_engine_kv_blocks_cached",
            "Refcount-0 paged-KV blocks retained for prefix reuse",
            kv_cached,
        ))
    for name, desc, value in series:
        _gauge(name, desc, ENGINE_TAGS).set(
            float(value), tags=tags
        )


# ---------------------------------------------------------------------
# read side: fold the head's metric table into per-deployment rows
# ---------------------------------------------------------------------

def _tag_dict(flat: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    if not flat:
        return out
    for part in flat.split("|"):
        key, _, value = part.partition("=")
        out[key] = value
    return out


def deployment_snapshot(summary: Dict[str, dict]) -> Dict[tuple, dict]:
    """Fold a `metrics_summary()` mapping into {(app, deployment):
    {p50_ms, p99_ms, requests_total, errors_total, executing, ...}} —
    the request-path half of `serve.status()` / `/api/serve`."""
    out: Dict[tuple, dict] = {}

    def row(tags: Dict[str, str]) -> Optional[dict]:
        app = tags.get("app")
        deployment = tags.get("deployment")
        if app is None or deployment is None or not deployment:
            return None
        return out.setdefault(
            (app, deployment),
            {
                "requests_total": 0.0,
                "errors_total": 0.0,
                "executing": 0.0,
            },
        )

    latency = summary.get("serve_request_latency_ms", {})
    for flat, series in (latency.get("by_tags") or {}).items():
        target = row(_tag_dict(flat))
        if target is None:
            continue
        for stat in ("p50", "p99"):
            if stat in series:
                target[f"{stat}_ms"] = series[stat]
        target["mean_ms"] = round(
            series.get("sum", 0.0) / series["count"], 3
        ) if series.get("count") else 0.0

    counts = summary.get("serve_requests_total", {})
    for flat, series in (counts.get("by_tags") or {}).items():
        tags = _tag_dict(flat)
        target = row(tags)
        if target is None:
            continue
        total = float(series.get("total", 0.0) or 0.0)
        target["requests_total"] += total
        if tags.get("outcome") == "error":
            target["errors_total"] += total

    executing = summary.get("serve_replica_executing", {})
    for flat, series in (executing.get("by_tags") or {}).items():
        target = row(_tag_dict(flat))
        if target is None:
            continue
        target["executing"] += float(series.get("value", 0.0) or 0.0)

    queue_wait = summary.get("serve_queue_wait_ms", {})
    for flat, series in (queue_wait.get("by_tags") or {}).items():
        target = row(_tag_dict(flat))
        if target is None or not series.get("count"):
            continue
        target["queue_wait_p50_ms"] = series.get("p50", 0.0)

    model_load = summary.get("serve_model_load_ms", {})
    for flat, series in (model_load.get("by_tags") or {}).items():
        target = row(_tag_dict(flat))
        if target is None or not series.get("count"):
            continue
        target["model_loads"] = series.get("count", 0)
        target["model_load_p50_ms"] = series.get("p50", 0.0)

    _fold_engine(summary, row, out)
    return out


def _fold_engine(summary: Dict[str, dict], row, out) -> None:
    """Continuous-batching engine series -> per-deployment rows: a
    nested per-family breakdown plus summed top-level occupancy (the
    at-a-glance numbers `/api/serve` and `serve.status()` show)."""

    def family_row(tags: Dict[str, str]) -> Optional[dict]:
        target = row(tags)
        if target is None:
            return None
        families = target.setdefault("engine", {})
        return families.setdefault(tags.get("family", "default"), {})

    def fold(metric: str, fn) -> None:
        for flat, series in (
            summary.get(metric, {}).get("by_tags") or {}
        ).items():
            tags = _tag_dict(flat)
            target = family_row(tags)
            if target is not None:
                fn(target, series)

    fold(
        "serve_engine_slots_used",
        lambda t, s: t.__setitem__(
            "slots_used", float(s.get("value", 0.0) or 0.0)
        ),
    )
    fold(
        "serve_engine_slots_total",
        lambda t, s: t.__setitem__(
            "slots_total", float(s.get("value", 0.0) or 0.0)
        ),
    )
    fold(
        "serve_engine_waiting",
        lambda t, s: t.__setitem__(
            "waiting", float(s.get("value", 0.0) or 0.0)
        ),
    )
    fold(
        "serve_engine_tokens_total",
        lambda t, s: t.__setitem__(
            "tokens_total", float(s.get("total", 0.0) or 0.0)
        ),
    )
    fold(
        "serve_engine_kv_blocks_used",
        lambda t, s: t.__setitem__(
            "kv_blocks_used", float(s.get("value", 0.0) or 0.0)
        ),
    )
    fold(
        "serve_engine_kv_blocks_total",
        lambda t, s: t.__setitem__(
            "kv_blocks_total", float(s.get("value", 0.0) or 0.0)
        ),
    )
    fold(
        "serve_engine_prefix_hits_total",
        lambda t, s: t.__setitem__(
            "prefix_hits", float(s.get("total", 0.0) or 0.0)
        ),
    )
    fold(
        "serve_engine_prefix_misses_total",
        lambda t, s: t.__setitem__(
            "prefix_misses", float(s.get("total", 0.0) or 0.0)
        ),
    )

    def histo(target: dict, series: dict, prefix: str) -> None:
        if not series.get("count"):
            return
        target[f"{prefix}_p50"] = series.get("p50", 0.0)
        if "p99" in series:
            target[f"{prefix}_p99"] = series["p99"]

    fold(
        "serve_engine_step_batch",
        lambda t, s: histo(t, s, "batch"),
    )
    fold(
        "serve_engine_decode_step_ms",
        lambda t, s: histo(t, s, "decode_ms"),
    )
    fold(
        "serve_engine_ttft_ms",
        lambda t, s: histo(t, s, "ttft_ms"),
    )

    # Summed top-level occupancy per deployment (families collapse
    # into the at-a-glance columns).
    for target in out.values():
        families = target.get("engine")
        if not families:
            continue
        for key in (
            "slots_used", "slots_total", "waiting", "tokens_total",
            "kv_blocks_used", "kv_blocks_total",
            "prefix_hits", "prefix_misses",
        ):
            target[f"engine_{key}"] = sum(
                f.get(key, 0.0) for f in families.values()
            )
