"""Serve controller actor.

Reference: python/ray/serve/_private/controller.py:84 — a singleton
controller reconciles declared application/deployment state against
live replica actors (deployment_state.py), autoscales on reported
ongoing-request load (autoscaling_state.py), and serves route +
replica-membership lookups to routers/proxies via LONG-POLL PUSH
(long_poll.py LongPollHost: listeners block on a snapshot id and are
released the moment state changes — no TTL staleness window).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

CONTROLLER_NAME = "SERVE_CONTROLLER"

#: Server-side cap on one long-poll blocking call; listeners loop.
LONG_POLL_TIMEOUT_S = 30.0


class ServeController:
    def __init__(self):
        import ray_tpu as rt

        self._rt = rt
        self._lock = threading.RLock()
        # apps[app] = {"route_prefix", "ingress", "deployments": {name: spec}}
        self._apps: Dict[str, dict] = {}
        # replicas[(app, dep)] = [{"id", "actor", "version"}]
        self._replicas: Dict[Tuple[str, str], List[dict]] = {}
        # handle metrics: {(app, dep): {handle_id: (ts, ongoing)}}
        self._metrics: Dict[Tuple[str, str], Dict[str, tuple]] = {}
        self._desired_since: Dict[Tuple[str, str], tuple] = {}
        self._replica_seq = 0
        self._shutdown = False
        # Long-poll host state (reference: long_poll.py LongPollHost):
        # every pushable key has a monotonically increasing snapshot
        # id; listeners block on the condvar until a key they watch
        # moves past the id they already have.
        self._snapshot_ids: Dict[str, int] = {}
        self._longpoll_cv = threading.Condition(self._lock)
        self._autoscaler = threading.Thread(
            target=self._autoscale_loop, daemon=True
        )
        self._autoscaler.start()

    # -- deploy --------------------------------------------------------
    def deploy_app(
        self, app_name: str, route_prefix: Optional[str], specs: List[dict]
    ) -> bool:
        with self._lock:
            ingress = next(s["name"] for s in specs if s.get("ingress"))
            self._apps[app_name] = {
                "route_prefix": route_prefix,
                "ingress": ingress,
                "deployments": {s["name"]: s for s in specs},
            }
        for spec in specs:
            self._reconcile_deployment(app_name, spec)
        self._bump(
            "routes",
            *(f"spec:{app_name}/{s['name']}" for s in specs),
        )
        return True

    def _reconcile_deployment(self, app: str, spec: dict) -> None:
        key = (app, spec["name"])
        with self._lock:
            existing = self._replicas.setdefault(key, [])
            # Version change: replace every replica (reference:
            # deployment_state rolling update, simplified to recreate).
            stale = [
                r for r in existing if r["version"] != spec["version"]
            ]
            keep = [r for r in existing if r["version"] == spec["version"]]
            self._replicas[key] = keep
        for replica in stale:
            self._stop_replica(replica)
        if stale:
            self._bump(f"replicas:{app}/{spec['name']}")
        target = spec["num_replicas"]
        if spec.get("autoscaling"):
            target = max(
                spec["autoscaling"]["min_replicas"],
                min(target, spec["autoscaling"]["max_replicas"]),
            )
        self._scale_to(app, spec, target)

    def _scale_to(self, app: str, spec: dict, target: int) -> None:
        key = (app, spec["name"])
        while True:
            with self._lock:
                current = len(self._replicas[key])
                if current >= target:
                    excess = self._replicas[key][target:]
                    self._replicas[key] = self._replicas[key][:target]
                else:
                    excess = None
            if excess is not None:
                for replica in excess:
                    self._stop_replica(replica)
                if excess:
                    self._bump(f"replicas:{app}/{spec['name']}")
                return
            self._start_replica(app, spec)

    def _start_replica(self, app: str, spec: dict) -> None:
        import cloudpickle

        from .replica import Replica

        with self._lock:
            self._replica_seq += 1
            replica_id = f"{app}#{spec['name']}#{self._replica_seq}"
        options = dict(spec.get("actor_options") or {})
        options.setdefault("num_cpus", 1)
        # Replicas interleave requests up to max_ongoing_requests via
        # actor concurrency (reference: serve replicas are async actors
        # bounded by max_ongoing_requests) — before max_concurrency
        # existed, batching had to live handle-side in the router.
        options.setdefault(
            "max_concurrency", int(spec.get("max_ongoing_requests") or 8)
        )
        actor_cls = self._rt.remote(**options)(Replica)
        handle = actor_cls.remote(
            cloudpickle.loads(spec["cls_blob"]),
            spec["init_args"],
            spec["init_kwargs"],
            replica_id,
            app_name=app,
            deployment_name=spec["name"],
        )
        # Block until the replica's constructor ran (readiness probe);
        # it reports its node so routers can prefer local replicas.
        node_id = self._rt.get(handle.node_id.remote(), timeout=60)
        with self._lock:
            self._replicas[(app, spec["name"])].append(
                {
                    "id": replica_id,
                    "actor": handle,
                    "version": spec["version"],
                    "node_id": node_id,
                }
            )
        self._bump(f"replicas:{app}/{spec['name']}")

    def _stop_replica(self, replica: dict) -> None:
        try:
            self._rt.kill(replica["actor"])
        except Exception:
            pass

    # -- long poll -----------------------------------------------------
    def _bump(self, *keys: str) -> None:
        """Advance snapshot ids and release blocked listeners (caller
        need not hold the lock)."""
        with self._longpoll_cv:
            for key in keys:
                self._snapshot_ids[key] = (
                    self._snapshot_ids.get(key, 0) + 1
                )
            self._longpoll_cv.notify_all()

    def _snapshot_value(self, key: str):
        if key == "routes":
            return self.get_routes()
        kind, _, rest = key.partition(":")
        if kind == "replicas":
            app, _, dep = rest.partition("/")
            return self.get_replicas(app, dep)
        if kind == "spec":
            app, _, dep = rest.partition("/")
            try:
                return self.get_deployment_spec(app, dep)
            except KeyError:
                return None
        raise ValueError(f"unknown long-poll key {key!r}")

    def listen_for_change(self, watched: Dict[str, int]) -> dict:
        """Block until any watched key's snapshot id exceeds the
        caller's, then return {key: {"snapshot_id", "value"}} for the
        changed keys; {} on server-side timeout (caller re-arms).
        Runs on the controller's thread pool (max_concurrency), so
        many routers/proxies can hold open polls concurrently
        (reference: long_poll.py LongPollHost.listen_for_change)."""
        deadline = time.time() + LONG_POLL_TIMEOUT_S
        with self._longpoll_cv:
            while not self._shutdown:
                changed = {
                    key: seen
                    for key, seen in watched.items()
                    if self._snapshot_ids.get(key, 0) > seen
                }
                if changed:
                    break
                remaining = deadline - time.time()
                if remaining <= 0:
                    return {}
                self._longpoll_cv.wait(timeout=remaining)
            if self._shutdown:
                return {}
            out = {}
            for key in changed:
                out[key] = {
                    "snapshot_id": self._snapshot_ids.get(key, 0),
                    "value": self._snapshot_value(key),
                }
            return out

    # -- lookups -------------------------------------------------------
    def get_routes(self) -> Dict[str, Tuple[str, str]]:
        with self._lock:
            return {
                state["route_prefix"]: (app, state["ingress"])
                for app, state in self._apps.items()
                if state["route_prefix"]
            }

    def get_replicas(self, app: str, deployment: str) -> List[dict]:
        with self._lock:
            return [
                {
                    "id": r["id"],
                    "actor": r["actor"],
                    "node_id": r.get("node_id"),
                    # Multiplexed model ids loaded on this replica;
                    # routers prefer warm holders (reference:
                    # multiplexed_replicas ranking in the replica
                    # scheduler).
                    "model_ids": list(r.get("model_ids", ())),
                }
                for r in self._replicas.get((app, deployment), [])
            ]

    def record_multiplexed(
        self,
        app: str,
        deployment: str,
        replica_id: str,
        model_ids: List[str],
    ) -> bool:
        """A replica's multiplex LRU changed; push the new holder set
        to routers over the replicas long-poll key (reference:
        replicas push model ids via controller long-poll)."""
        with self._lock:
            for r in self._replicas.get((app, deployment), []):
                if r["id"] == replica_id:
                    r["model_ids"] = list(model_ids)
                    break
            else:
                return False
        self._bump(f"replicas:{app}/{deployment}")
        return True

    def get_deployment_spec(self, app: str, deployment: str) -> dict:
        with self._lock:
            spec = self._apps[app]["deployments"][deployment]
            return {
                k: spec.get(k)
                for k in (
                    "name",
                    "num_replicas",
                    "version",
                    "batched_methods",
                    "autoscaling",
                    "ingress_streaming",
                )
            }

    def _in_flight(self, app: str, name: str, now: float) -> float:
        """Requests currently routed-but-unresolved for a deployment:
        the sum of every handle's freshly-reported ongoing count
        (stale handles — exited drivers — age out of the sum). Caller
        holds the lock."""
        return sum(
            value
            for ts, value in self._metrics.get(
                (app, name), {}
            ).values()
            if now - ts < 2.0
        )

    def status(self) -> dict:
        now = time.time()
        with self._lock:
            return {
                app: {
                    "route_prefix": state["route_prefix"],
                    "deployments": {
                        name: {
                            "replicas": len(
                                self._replicas.get((app, name), [])
                            ),
                            "version": spec["version"],
                            "in_flight": self._in_flight(
                                app, name, now
                            ),
                        }
                        for name, spec in state["deployments"].items()
                    },
                }
                for app, state in self._apps.items()
            }

    # -- autoscaling ---------------------------------------------------
    def report_metrics(
        self, app: str, deployment: str, handle_id: str, ongoing: float
    ) -> None:
        with self._lock:
            self._metrics.setdefault((app, deployment), {})[
                handle_id
            ] = (time.time(), ongoing)

    def _autoscale_loop(self) -> None:
        while not self._shutdown:
            time.sleep(0.25)
            try:
                self._autoscale_tick()
            except Exception:
                pass

    def _autoscale_tick(self) -> None:
        now = time.time()
        with self._lock:
            work = []
            for app, state in self._apps.items():
                for name, spec in state["deployments"].items():
                    cfg = spec.get("autoscaling")
                    if not cfg:
                        continue
                    ongoing = self._in_flight(app, name, now)
                    current = len(self._replicas.get((app, name), []))
                    desired = max(
                        cfg["min_replicas"],
                        min(
                            cfg["max_replicas"],
                            math.ceil(
                                ongoing
                                / max(
                                    cfg["target_ongoing_requests"], 1e-9
                                )
                            ),
                        ),
                    )
                    key = (app, name)
                    prev = self._desired_since.get(key)
                    if prev is None or prev[0] != desired:
                        self._desired_since[key] = (desired, now)
                        continue
                    held = now - prev[1]
                    delay = (
                        cfg["upscale_delay_s"]
                        if desired > current
                        else cfg["downscale_delay_s"]
                    )
                    if desired != current and held >= delay:
                        work.append((app, dict(spec), desired))
        for app, spec, desired in work:
            self._scale_to(app, spec, desired)

    # -- teardown ------------------------------------------------------
    def delete_app(self, app_name: str) -> bool:
        with self._lock:
            state = self._apps.pop(app_name, None)
            if state is None:
                return False
            keys = [
                (app_name, name) for name in state["deployments"]
            ]
            doomed = []
            for key in keys:
                doomed.extend(self._replicas.pop(key, []))
        for replica in doomed:
            self._stop_replica(replica)
        self._bump(
            "routes", *(f"replicas:{app}/{dep}" for app, dep in keys)
        )
        return True

    def shutdown_all(self) -> bool:
        with self._lock:
            apps = list(self._apps)
        for app in apps:
            self.delete_app(app)
        self._shutdown = True
        with self._longpoll_cv:  # release all blocked listeners
            self._longpoll_cv.notify_all()
        return True
