"""gRPC ingress for serve proxies.

Reference: python/ray/serve/_private/proxy.py:431 (gRPCProxy: the
per-node proxy terminates gRPC alongside HTTP) + grpc_util/ — requests
route by application name carried in call metadata, the same model the
reference uses (`application` metadata key), plus the built-in
RayServeAPIService surface (Healthz / ListApplications).

Implementation notes: the service is registered with grpc's GENERIC
handler API and bytes-identity (de)serializers, so no generated stubs
are required on either side — any grpc client (any language) calls
`/ray.serve.RayServeAPIService/...` with bytes payloads. Request
payloads are passed to the ingress deployment as-is (bytes); replies
are the deployment's return value (bytes passed through, str utf-8,
everything else JSON). `multiplexed_model_id` metadata maps to the
router's model-aware replica ranking exactly like the HTTP header.
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Any, Callable, Dict, Optional

SERVICE = "ray.serve.RayServeAPIService"


def _encode_reply(value: Any) -> bytes:
    if isinstance(value, bytes):
        return value
    if isinstance(value, str):
        return value.encode()
    return json.dumps(value, default=str).encode()


class GrpcIngress:
    """A grpc.Server routing Predict calls to application handles.

    `handle_for(app_name)` -> DeploymentHandle (or None), provided by
    the owning proxy; `app_names()` lists live applications.
    """

    def __init__(
        self,
        port: int,
        handle_for: Callable[[str], Optional[Any]],
        app_names: Callable[[], list],
        host: str = "127.0.0.1",
    ):
        import grpc

        self._handle_for = handle_for
        self._app_names = app_names

        def predict(request: bytes, context) -> bytes:
            metadata = dict(context.invocation_metadata())
            app = metadata.get("application", "")
            model_id = metadata.get("multiplexed_model_id", "")
            handle = self._handle_for(app)
            if handle is None:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"no serve application {app!r}",
                )
            if model_id:
                handle = handle.options(
                    multiplexed_model_id=model_id
                )
            value = handle.remote(request).result(timeout=60)
            return _encode_reply(value)

        def healthz(request: bytes, context) -> bytes:
            return b"success"

        def list_applications(request: bytes, context) -> bytes:
            return json.dumps(sorted(self._app_names())).encode()

        rpcs = {
            "Predict": predict,
            "Healthz": healthz,
            "ListApplications": list_applications,
        }
        identity = lambda b: b  # noqa: E731 — bytes on the wire

        method_handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=identity,
                response_serializer=identity,
            )
            for name, fn in rpcs.items()
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8)
        )
        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    SERVICE, method_handlers
                ),
            )
        )
        bound = self._server.add_insecure_port(f"{host}:{port}")
        if bound == 0:
            raise OSError(f"could not bind gRPC ingress on {port}")
        self.port = bound
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)


def grpc_methods(channel):
    """Client-side callables for the ingress service over an existing
    grpc channel — bytes in / bytes out, no generated stubs needed::

        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        predict, healthz, list_apps = grpc_methods(channel)
        reply = predict(b"payload",
                        metadata=[("application", "myapp")])
    """
    identity = lambda b: b  # noqa: E731

    def unary(name):
        return channel.unary_unary(
            f"/{SERVICE}/{name}",
            request_serializer=identity,
            response_deserializer=identity,
        )

    return (
        unary("Predict"),
        unary("Healthz"),
        unary("ListApplications"),
    )
