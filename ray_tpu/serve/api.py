"""Serve public API.

Reference: python/ray/serve/api.py — serve.run(app) deploys through
the controller and returns the ingress handle (:492); serve.start
brings up HTTP ingress; status/delete/shutdown manage lifecycle.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Dict, Optional

import cloudpickle

from .controller import CONTROLLER_NAME, ServeController
from .deployment import Application, AutoscalingConfig, Deployment
from .proxy import Proxy
from .replica import HandleRef
from .router import DeploymentHandle

PROXY_NAME = "SERVE_PROXY"


def _proxy_name(node_id: str) -> str:
    """Deterministic per-node proxy actor name. Keyed ONLY on the
    node id — never on which driver called start() — so any driver on
    any node resolves (and shuts down) every proxy (reference:
    proxy_state.py names proxies by node id for the same reason)."""
    return f"{PROXY_NAME}:{node_id[:12]}"
_NAMESPACE = "serve"


def _rt():
    import ray_tpu as rt

    if not rt.is_initialized():
        rt.init(ignore_reinit_error=True)
    return rt


def _get_or_create_controller():
    rt = _rt()
    try:
        return rt.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)
    except ValueError:
        pass
    # Long-poll listeners (one per router/proxy) BLOCK inside
    # listen_for_change; the controller must run them on a wide
    # thread pool or one parked listener starves every control call
    # (reference: the controller is an async actor).
    actor_cls = rt.remote(
        num_cpus=0,
        name=CONTROLLER_NAME,
        namespace=_NAMESPACE,
        max_concurrency=64,
    )(ServeController)
    handle = actor_cls.remote()
    # Touch it so creation completed before anyone races lookups.
    rt.get(handle.status.remote(), timeout=60)
    return handle


def _build_specs(app: Application, app_name: str):
    """Flatten the bound graph into deployment specs; nested bound
    deployments become HandleRefs materialized in the replica
    (reference: build_app + handle injection)."""
    flat = app.flatten()
    specs = []
    for bound in flat:
        dep: Deployment = bound.deployment

        def convert(value):
            if isinstance(value, Application):
                return HandleRef(app_name, value.deployment.name)
            return value

        batched = {}
        for attr_name in dir(dep.underlying):
            attr = getattr(dep.underlying, attr_name, None)
            cfg = getattr(attr, "__rt_serve_batch__", None)
            if cfg:
                batched[attr_name] = cfg
        specs.append(
            {
                "name": dep.name,
                "cls_blob": cloudpickle.dumps(dep.underlying),
                "init_args": tuple(convert(a) for a in bound.args),
                "init_kwargs": {
                    k: convert(v) for k, v in bound.kwargs.items()
                },
                "num_replicas": dep.num_replicas,
                "actor_options": dep.ray_actor_options,
                "autoscaling": dataclasses.asdict(dep.autoscaling_config)
                if dep.autoscaling_config
                else None,
                "max_ongoing_requests": dep.max_ongoing_requests,
                "version": dep.version,
                "batched_methods": batched,
                "ingress": bound is flat[-1],
                # Generator __call__ => the proxy streams the response
                # out as chunked transfer-encoding (reference: serve
                # supports generator deployments for streaming).
                "ingress_streaming": inspect.isgeneratorfunction(
                    getattr(dep.underlying, "__call__", None)
                ),
            }
        )
    return specs


def run(
    app: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = "/",
) -> DeploymentHandle:
    rt = _rt()
    controller = _get_or_create_controller()
    specs = _build_specs(app, name)
    rt.get(
        controller.deploy_app.remote(name, route_prefix, specs),
        timeout=120,
    )
    return DeploymentHandle(name, app.deployment.name)


def start(
    http_port: int = 8000,
    per_node: bool = True,
    http_host: str = "127.0.0.1",
    grpc_port: Optional[int] = None,
) -> int:
    """Start HTTP proxies — one per alive node, each pinned with node
    affinity and routing to LOCAL replicas first (reference:
    serve.start + proxy_state.py per-node ProxyActors). Returns the
    port of this node's proxy. Pass http_host="0.0.0.0" on a real
    multi-host cluster so every node's proxy is reachable from
    outside its host. On in-box test clusters (all daemons on one
    host) the extra proxies take ephemeral ports when http_port is
    already bound; query them via `proxy_ports()`. The LOCAL proxy
    never silently rebinds — a port conflict on this node raises."""
    from ..util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    rt = _rt()
    _get_or_create_controller()
    local_node = rt.get_runtime_context().get_node_id()
    node_ids = (
        [n["node_id"] for n in rt.nodes() if n.get("alive")]
        if per_node
        else [local_node]
    )
    local_port = None
    for node_id in node_ids:
        name = _proxy_name(node_id)
        try:
            proxy = rt.get_actor(name, namespace=_NAMESPACE)
        except ValueError:
            actor_cls = rt.remote(
                num_cpus=0,
                name=name,
                namespace=_NAMESPACE,
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=node_id
                ),
            )(Proxy)
            proxy = actor_cls.remote(
                http_port,
                node_id != local_node,  # extras may take ephemeral
                http_host,
                grpc_port,
            )
        port = rt.get(proxy.ready.remote(), timeout=60)
        if node_id == local_node:
            local_port = port
    return local_port if local_port is not None else http_port


def local_grpc_port() -> Optional[int]:
    """Bound gRPC ingress port of this node's proxy (None when
    serve.start ran without grpc_port)."""
    rt = _rt()
    node_id = rt.get_runtime_context().get_node_id()
    try:
        proxy = rt.get_actor(_proxy_name(node_id), namespace=_NAMESPACE)
        return rt.get(proxy.grpc_ready.remote(), timeout=30)
    except Exception:
        return None


def proxy_ports() -> Dict[str, int]:
    """node_id -> bound proxy port for every running proxy."""
    rt = _rt()
    out: Dict[str, int] = {}
    for node in rt.nodes():
        node_id = node["node_id"]
        name = _proxy_name(node_id)
        try:
            proxy = rt.get_actor(name, namespace=_NAMESPACE)
            out[node_id] = rt.get(proxy.ready.remote(), timeout=30)
        except Exception:
            continue
    return out


def status() -> Dict[str, Any]:
    """Live per-app state: route prefix plus, per deployment, replica
    count, version, in-flight request count (router-reported), and —
    when request-path metrics have reached the head — p50/p99 handler
    latency, request/error totals and derived queue depth. The raw
    shape under ``{app: {"deployments": {name: {...}}}}`` is stable;
    metric keys appear once traffic has flowed."""
    rt = _rt()
    controller = _get_or_create_controller()
    base = rt.get(controller.status.remote(), timeout=30)
    return _merge_request_metrics(base)


def _merge_request_metrics(base: Dict[str, Any]) -> Dict[str, Any]:
    """Fold the head's serve histograms (observability.py) into the
    controller's structural status. Best-effort: a head that has seen
    no serve metrics yet (or an uninitialized summary read) leaves the
    structural status intact."""
    from .observability import deployment_snapshot

    try:
        from ..util.metrics import metrics_summary

        snapshot = deployment_snapshot(metrics_summary())
    except Exception:
        return base
    for app, state in base.items():
        for name, dep in (state.get("deployments") or {}).items():
            row = snapshot.get((app, name))
            if not row:
                continue
            dep.update(row)
            # Queue depth = routed-but-not-yet-executing: requests a
            # router has sent that no replica is running yet (actor
            # mailbox + wire). Derived, so the proxy/router never pays
            # a queue-tracking RPC.
            dep["queue_depth"] = max(
                0.0,
                float(dep.get("in_flight", 0.0))
                - float(row.get("executing", 0.0)),
            )
    return base


def status_detail() -> Dict[str, Any]:
    """`/api/serve` payload: `status()` flattened to one row per
    deployment (app/deployment in the row), empty when serve was
    never started on this cluster."""
    import ray_tpu as rt

    try:
        rt.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)
    except Exception:
        return {}
    out: Dict[str, Any] = {}
    for app, state in status().items():
        for name, dep in (state.get("deployments") or {}).items():
            out[f"{app}/{name}"] = {
                "route_prefix": state.get("route_prefix"),
                **dep,
            }
    # Per-family engine compile counts from the head's compile-watch
    # table (ISSUE 15): the engine registers its jitted programs as
    # engine.<kind>[<family>], so the cluster-folded counts are
    # already on the head — no per-replica RPC. A count that moves
    # under steady traffic is a mid-traffic recompile, i.e. an
    # engine bug, now visible next to the deployment rows.
    try:
        from ..util.state import compile_summary

        for prog, row in sorted(
            compile_summary().get("programs", {}).items()
        ):
            if not prog.startswith("engine."):
                continue
            kind, _, family = prog[len("engine."):].partition("[")
            family = family.rstrip("]") or "default"
            entry = out.setdefault(
                f"engine:{family}", {"family": family}
            )
            entry[f"{kind}_compiles"] = row.get("compiles", 0)
            entry[f"{kind}_shapes"] = row.get("distinct_shapes", 0)
    except Exception:  # noqa: BLE001 — status must not need compiles
        pass
    return out


def get_app_handle(name: str = "default") -> DeploymentHandle:
    rt = _rt()
    controller = _get_or_create_controller()
    state = rt.get(controller.status.remote(), timeout=30)
    if name not in state:
        raise ValueError(f"no application {name!r}")
    routes = rt.get(controller.get_routes.remote(), timeout=30)
    for _, (app, ingress) in routes.items():
        if app == name:
            return DeploymentHandle(name, ingress)
    # Route-less app: find its ingress via status order.
    raise ValueError(f"application {name!r} has no ingress route")


def delete(name: str) -> None:
    rt = _rt()
    controller = _get_or_create_controller()
    rt.get(controller.delete_app.remote(name), timeout=60)


def shutdown() -> None:
    from . import router as _router

    rt = _rt()
    # Stop this process's long-poll listener threads (new handles
    # created by a later deploy start fresh listeners).
    _router.notify_shutdown()
    try:
        controller = rt.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)
    except ValueError:
        return
    try:
        rt.get(controller.shutdown_all.remote(), timeout=60)
    except Exception:
        pass
    # Kill every per-node proxy (names are node-id-keyed, so any
    # driver — not just the one that called start() — finds them all).
    names = []
    try:
        names = [_proxy_name(n["node_id"]) for n in rt.nodes()]
    except Exception:
        pass
    for name in names:
        try:
            proxy = rt.get_actor(name, namespace=_NAMESPACE)
            rt.get(proxy.stop.remote(), timeout=10)
            rt.kill(proxy)
        except Exception:
            continue
    try:
        rt.kill(controller)
    except Exception:
        pass
