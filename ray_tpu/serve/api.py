"""Serve public API.

Reference: python/ray/serve/api.py — serve.run(app) deploys through
the controller and returns the ingress handle (:492); serve.start
brings up HTTP ingress; status/delete/shutdown manage lifecycle.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import cloudpickle

from .controller import CONTROLLER_NAME, ServeController
from .deployment import Application, AutoscalingConfig, Deployment
from .proxy import Proxy
from .replica import HandleRef
from .router import DeploymentHandle

PROXY_NAME = "SERVE_PROXY"
_NAMESPACE = "serve"


def _rt():
    import ray_tpu as rt

    if not rt.is_initialized():
        rt.init(ignore_reinit_error=True)
    return rt


def _get_or_create_controller():
    rt = _rt()
    try:
        return rt.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)
    except ValueError:
        pass
    actor_cls = rt.remote(
        num_cpus=0, name=CONTROLLER_NAME, namespace=_NAMESPACE
    )(ServeController)
    handle = actor_cls.remote()
    # Touch it so creation completed before anyone races lookups.
    rt.get(handle.status.remote(), timeout=60)
    return handle


def _build_specs(app: Application, app_name: str):
    """Flatten the bound graph into deployment specs; nested bound
    deployments become HandleRefs materialized in the replica
    (reference: build_app + handle injection)."""
    flat = app.flatten()
    specs = []
    for bound in flat:
        dep: Deployment = bound.deployment

        def convert(value):
            if isinstance(value, Application):
                return HandleRef(app_name, value.deployment.name)
            return value

        batched = {}
        for attr_name in dir(dep.underlying):
            attr = getattr(dep.underlying, attr_name, None)
            cfg = getattr(attr, "__rt_serve_batch__", None)
            if cfg:
                batched[attr_name] = cfg
        specs.append(
            {
                "name": dep.name,
                "cls_blob": cloudpickle.dumps(dep.underlying),
                "init_args": tuple(convert(a) for a in bound.args),
                "init_kwargs": {
                    k: convert(v) for k, v in bound.kwargs.items()
                },
                "num_replicas": dep.num_replicas,
                "actor_options": dep.ray_actor_options,
                "autoscaling": dataclasses.asdict(dep.autoscaling_config)
                if dep.autoscaling_config
                else None,
                "max_ongoing_requests": dep.max_ongoing_requests,
                "version": dep.version,
                "batched_methods": batched,
                "ingress": bound is flat[-1],
            }
        )
    return specs


def run(
    app: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = "/",
) -> DeploymentHandle:
    rt = _rt()
    controller = _get_or_create_controller()
    specs = _build_specs(app, name)
    rt.get(
        controller.deploy_app.remote(name, route_prefix, specs),
        timeout=120,
    )
    return DeploymentHandle(name, app.deployment.name)


def start(http_port: int = 8000) -> int:
    """Start the HTTP proxy; returns the bound port (reference:
    serve.start + ProxyActor per node)."""
    rt = _rt()
    _get_or_create_controller()
    try:
        proxy = rt.get_actor(PROXY_NAME, namespace=_NAMESPACE)
    except ValueError:
        actor_cls = rt.remote(
            num_cpus=0, name=PROXY_NAME, namespace=_NAMESPACE
        )(Proxy)
        proxy = actor_cls.remote(http_port)
    return rt.get(proxy.ready.remote(), timeout=60)


def status() -> Dict[str, Any]:
    rt = _rt()
    controller = _get_or_create_controller()
    return rt.get(controller.status.remote(), timeout=30)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    rt = _rt()
    controller = _get_or_create_controller()
    state = rt.get(controller.status.remote(), timeout=30)
    if name not in state:
        raise ValueError(f"no application {name!r}")
    routes = rt.get(controller.get_routes.remote(), timeout=30)
    for _, (app, ingress) in routes.items():
        if app == name:
            return DeploymentHandle(name, ingress)
    # Route-less app: find its ingress via status order.
    raise ValueError(f"application {name!r} has no ingress route")


def delete(name: str) -> None:
    rt = _rt()
    controller = _get_or_create_controller()
    rt.get(controller.delete_app.remote(name), timeout=60)


def shutdown() -> None:
    rt = _rt()
    try:
        controller = rt.get_actor(CONTROLLER_NAME, namespace=_NAMESPACE)
    except ValueError:
        return
    try:
        rt.get(controller.shutdown_all.remote(), timeout=60)
    except Exception:
        pass
    try:
        proxy = rt.get_actor(PROXY_NAME, namespace=_NAMESPACE)
        rt.get(proxy.stop.remote(), timeout=10)
        rt.kill(proxy)
    except Exception:
        pass
    try:
        rt.kill(controller)
    except Exception:
        pass
