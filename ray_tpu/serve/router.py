"""DeploymentHandle + least-outstanding-tokens routing.

Reference: python/ray/serve/handle.py (DeploymentHandle /
DeploymentResponse) and _private/replica_scheduler/pow_2_scheduler.py:52
for the candidate-selection skeleton (model-warm replicas first, then
replicas on THIS node). Routing itself (ISSUE 11) is by LEAST
OUTSTANDING TOKENS: the router keeps a per-replica estimate of queued
work in TOKENS (prompt + token budget parsed from LLM payloads, a
flat default otherwise), decays it as stream chunks come back, and
sends each request to the candidate with the smallest estimate — a
40-token chat turn and a 200-token completion stop counting as equal
load the way in-flight REQUEST counts made them
(`serve_routing_policy=pow2` restores power-of-two-choices on request
counts). The estimate is released on EVERY exit path — exhaustion,
`.close()`/abandon, stream error — and entries for replicas that left
the membership (engine death, redeploy) are pruned on the long-poll
push, so phantom load can't pile onto a dead or cancelled stream's
replica. SLO admission control rides the same estimate: when even the
least-loaded candidate is over `serve_slo_queue_threshold_tokens`,
`remote()` raises DeploymentOverloaded and the proxy sheds with
503 + Retry-After instead of queueing into TTFT collapse (kill
switch RT_serve_slo_admission_enabled).

Replica membership and deployment specs arrive by CONTROLLER PUSH
over a long-poll listener (reference: long_poll.py LongPollClient) —
a redeploy is visible here within one push round-trip, not a
cache-TTL window. Batched methods group concurrent calls handle-side
into one replica call (reference: serve/batching.py, relocated to the
router because replicas execute serially here).
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from .controller import CONTROLLER_NAME


class DeploymentOverloaded(RuntimeError):
    """Every candidate replica's outstanding-token estimate is over
    the SLO admission threshold; shed (HTTP: 503 + Retry-After)
    instead of queueing."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


#: Outstanding-token estimate for requests whose payload carries no
#: prompt/budget (non-LLM deployments): one flat unit of work.
DEFAULT_TOKEN_ESTIMATE = 64


#: Process-wide routing/admission config, resolved from the
#: environment ONCE (the router sits on every request's hot path —
#: re-scanning os.environ per call would tax every chunk of every
#: stream). Tests that monkeypatch RT_serve_* env vars call
#: _reset_config_cache().
_config_cache = None


def _serve_config():
    global _config_cache
    if _config_cache is None:
        from .._private.config import Config

        _config_cache = Config.from_env()
    return _config_cache


def _reset_config_cache() -> None:
    global _config_cache
    _config_cache = None


def estimate_request_tokens(args: tuple, kwargs: dict) -> int:
    """Outstanding-token estimate for one request: prompt length +
    token budget when the payload exposes them (LLM dict payloads and
    proxy Request bodies), DEFAULT_TOKEN_ESTIMATE otherwise. A
    heuristic for LOAD RANKING — it only needs to order replicas, not
    to be exact."""
    del kwargs
    payload = args[0] if args else None
    if hasattr(payload, "json"):
        try:
            payload = payload.json()
        except Exception:
            payload = None
    if isinstance(payload, dict):
        estimate = 0
        prompt = payload.get("prompt")
        if isinstance(prompt, (list, tuple, str)):
            estimate += len(prompt)
        budget = payload.get("max_new_tokens")
        if budget is not None:
            try:
                estimate += max(0, int(budget))
            except (TypeError, ValueError):
                pass
        elif estimate:
            estimate += DEFAULT_TOKEN_ESTIMATE
        if estimate > 0:
            return estimate
    return DEFAULT_TOKEN_ESTIMATE


def pick_least_outstanding(
    replicas: List[dict], outstanding: Dict[str, int]
) -> dict:
    """The routing policy, as a pure function (unit-tested in
    tests/test_router_policy.py): the candidate with the fewest
    estimated outstanding tokens, ties broken uniformly at random
    (reservoir over the tied prefix) so idle replicas share cold
    traffic instead of all of it landing on the first in list
    order."""
    best = None
    best_load = None
    ties = 0
    for replica in replicas:
        load = outstanding.get(replica["id"], 0)
        if best is None or load < best_load:
            best, best_load, ties = replica, load, 1
        elif load == best_load:
            ties += 1
            if random.random() < 1.0 / ties:
                best = replica
    return best


def _controller():
    import ray_tpu as rt

    return rt.get_actor(CONTROLLER_NAME, namespace="serve")


#: Bumped by serve.shutdown(): long-poll listener threads exit when
#: their start-time epoch is stale instead of retrying a dead
#: controller at 5 Hz forever.
_shutdown_epoch = 0


def notify_shutdown() -> None:
    global _shutdown_epoch
    _shutdown_epoch += 1


def _local_node_id() -> Optional[str]:
    try:
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()
    except Exception:
        return None


class DeploymentResponse:
    """Future for one request (reference: serve/handle.py
    DeploymentResponse.result())."""

    def __init__(self, waiter, router: "DeploymentHandle"):
        self._waiter = waiter  # callable(timeout) -> value
        self._router = router
        self._resolved = False
        self._released = False
        self._value = None
        self._tokens = 0  # outstanding-token estimate to release

    def _release(self) -> None:
        """Release the in-flight count + outstanding-token estimate
        exactly once — from result(), or from GC for a response the
        caller fired and dropped (without this, a handful of dropped
        responses would pin phantom load on a replica forever and
        eventually trip SLO admission into permanent 503s)."""
        if self._released:
            return
        self._released = True
        replica_id = getattr(self, "_replica_id", None)
        self._router._ongoing_done(replica_id)
        self._router._tokens_done(replica_id, self._tokens)
        self._tokens = 0

    def result(self, timeout: Optional[float] = 30.0):
        if not self._resolved:
            try:
                self._value = self._waiter(timeout)
            finally:
                self._release()
            self._resolved = True
        if isinstance(self._value, BaseException):
            raise self._value
        return self._value

    def __del__(self):
        self._release()


class DeploymentResponseGenerator:
    """Iterator over a streaming replica method's yields (reference:
    handle.py DeploymentResponseGenerator). Chunks arrive as the
    replica produces them — the transport is the runtime's streaming
    generator path, so a slow consumer doesn't buffer the whole
    response anywhere, and each stream is consumed independently: one
    stream blocking on its next chunk must never head-of-line block a
    sibling stream from the same (batched) replica — the
    continuous-batching engine serves many interleaved token streams
    from one replica (regression: test_serve.py
    test_interleaved_streams_not_serialized)."""

    def __init__(
        self,
        ref_gen,
        router: "DeploymentHandle",
        replica_id,
        actor=None,
        request_id: str = "",
        tokens: int = 0,
    ):
        self._gen = ref_gen
        self._router = router
        self._replica_id = replica_id
        self._actor = actor
        self._request_id = request_id
        self._tokens_left = int(tokens)
        self._finished = False
        self._exhausted = False

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu as rt

        if self._finished:
            raise StopIteration
        try:
            ref = next(self._gen)
            value = rt.get(ref, timeout=60)
        except StopIteration:
            self._exhausted = True
            self.close()
            raise
        except BaseException:
            self.close()
            raise
        # One chunk ≈ one token of the estimate done: the replica's
        # outstanding-token load decays AS the stream progresses, so
        # routing sees a request 90% through its budget as almost
        # free, not as a full request's worth of load.
        if self._tokens_left > 0:
            self._tokens_left -= 1
            self._router._tokens_done(self._replica_id, 1)
        return value

    def close(self) -> None:
        """Release the ongoing-count slot and the REMAINING
        outstanding-token estimate exactly once, and tell the replica
        when the stream was ABANDONED (client disconnect, break)
        rather than exhausted: a continuous-batching engine frees the
        request's KV slot mid-decode instead of decoding the rest of
        the token budget for nobody. The token release is the
        router-side half of that cancel path (ISSUE 11 phantom-load
        fix): without it an abandoned or engine-failed stream would
        keep its full remaining budget counted against the replica
        until process exit, skewing least-outstanding-tokens routing
        and SLO admission forever."""
        if self._finished:
            return
        self._finished = True
        self._router._ongoing_done(self._replica_id)
        self._router._tokens_done(self._replica_id, self._tokens_left)
        self._tokens_left = 0
        if (
            not self._exhausted
            and self._actor is not None
            and self._request_id
        ):
            try:
                ref = self._actor.cancel_stream.remote(
                    self._request_id
                )
                del ref  # fire-and-forget: cancel is best-effort
            except Exception:
                pass

    def __del__(self):
        self.close()


class _BatchQueue:
    """Handle-side batcher for @serve.batch methods."""

    def __init__(self, handle: "DeploymentHandle", method: str, cfg: dict):
        self._handle = handle
        self._method = method
        self._max = cfg["max_batch_size"]
        self._wait = cfg["batch_wait_timeout_s"]
        self._lock = threading.Lock()
        self._pending: List[dict] = []
        self._timer: Optional[threading.Timer] = None

    def submit(self, args: tuple) -> "DeploymentResponse":
        entry = {
            "args": args,
            "event": threading.Event(),
            "value": None,
        }
        flush_now = False
        with self._lock:
            self._pending.append(entry)
            if len(self._pending) >= self._max:
                flush_now = True
            elif self._timer is None:
                self._timer = threading.Timer(self._wait, self._flush)
                self._timer.daemon = True
                self._timer.start()
        if flush_now:
            self._flush()
        self._handle._ongoing_sent()

        def waiter(timeout):
            if not entry["event"].wait(timeout):
                raise TimeoutError(
                    f"batched call to {self._method} timed out"
                )
            return entry["value"]

        return DeploymentResponse(waiter, self._handle)

    def _flush(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            batch, self._pending = self._pending, []
        if not batch:
            return
        import ray_tpu as rt

        replica = self._handle._pick_replica()
        ref = replica["actor"].handle_batch.remote(
            self._method,
            [e["args"] for e in batch],
            self._handle._request_ctx(),
        )

        def deliver():
            try:
                values = rt.get(ref, timeout=60)
                if not isinstance(values, list) or len(values) != len(
                    batch
                ):
                    raise ValueError(
                        "@serve.batch method must return a list with "
                        "one output per input"
                    )
            except BaseException as e:  # noqa: BLE001 — forwarded
                values = [e] * len(batch)
            for entry, value in zip(batch, values):
                entry["value"] = value
                entry["event"].set()

        threading.Thread(target=deliver, daemon=True).start()


class DeploymentHandle:
    def __init__(
        self,
        app_name: str,
        deployment_name: str,
        method_name: str = "__call__",
    ):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._method = method_name
        self._handle_id = uuid.uuid4().hex[:8]
        self._lock = threading.Lock()
        # Replica membership + spec live in a SHARED mutable box so
        # every method clone of this handle family sees long-poll
        # pushes (clone-time attribute snapshots would strand clones
        # on killed replicas after a redeploy).
        self._state: Dict[str, Any] = {
            "replicas": [],
            "replicas_ts": 0.0,
            "spec": None,
        }
        self._ongoing: Dict[str, int] = {}  # replica_id -> in flight
        #: replica_id -> estimated outstanding TOKENS (the routing +
        #: SLO-admission signal; shared across method clones like
        #: _ongoing so one handle family sees one load picture).
        self._outstanding_tokens: Dict[str, int] = {}
        self._sent = 0
        self._done = 0
        self._batchers: Dict[str, _BatchQueue] = {}
        self._reporter: Optional[threading.Thread] = None
        # Mutable box shared across method clones (plain attributes
        # would be snapshotted at clone time): one listener per
        # handle family.
        self._listener_box: Dict[str, Any] = {"thread": None}
        self._stream = False
        self._model_id = ""  # multiplexed model id for this clone
        self._request_id = ""  # proxy-pinned request id, if any

    # -- routing -------------------------------------------------------
    def _refresh(self, force: bool = False) -> None:
        """Pull the current snapshot once, then keep it current by
        long-poll PUSH (the listener thread below)."""
        with self._lock:
            fresh = bool(self._state["replicas_ts"]) and not force
        if fresh:
            self._ensure_listener()
            return
        import ray_tpu as rt

        controller = _controller()
        replicas = rt.get(
            controller.get_replicas.remote(
                self.app_name, self.deployment_name
            ),
            timeout=30,
        )
        spec = rt.get(
            controller.get_deployment_spec.remote(
                self.app_name, self.deployment_name
            ),
            timeout=30,
        )
        with self._lock:
            self._state["replicas"] = replicas
            self._state["replicas_ts"] = time.time()
            self._state["spec"] = spec
            self._prune_gone_locked()
        self._ensure_listener()

    def _ensure_listener(self) -> None:
        with self._lock:
            if self._listener_box["thread"] is not None:
                return
            self._listener_box["thread"] = threading.Thread(
                target=self._listen_loop, daemon=True,
                name=f"serve-longpoll:{self.deployment_name}",
            )
            self._listener_box["thread"].start()

    def _listen_loop(self) -> None:
        """Long-poll client (reference: long_poll.py LongPollClient):
        each round blocks controller-side until replicas or spec
        change, then applies the pushed values."""
        import ray_tpu as rt

        dep = f"{self.app_name}/{self.deployment_name}"
        keys = {f"replicas:{dep}": 0, f"spec:{dep}": 0}
        epoch = _shutdown_epoch
        backoff = 0.2
        while epoch == _shutdown_epoch:
            try:
                controller = _controller()
                changed = rt.get(
                    controller.listen_for_change.remote(dict(keys)),
                    timeout=60,
                )
                backoff = 0.2
            except Exception:
                # Controller restart/redeploy window — or it is gone
                # for good; back off so a dead controller costs ~one
                # lookup per 5s, and exit on serve.shutdown().
                time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
                continue
            if not changed:
                continue
            with self._lock:
                for key, update in changed.items():
                    keys[key] = update["snapshot_id"]
                    if key.startswith("replicas:"):
                        self._state["replicas"] = update["value"] or []
                        self._state["replicas_ts"] = time.time()
                        # Replicas that left the membership (engine/
                        # replica death, redeploy) take their load
                        # estimates with them — their streams will
                        # never decrement, and phantom load on a dead
                        # id must not deter routing to its
                        # replacement (ISSUE 11 phantom-load fix).
                        self._prune_gone_locked()
                    elif update["value"] is not None:
                        self._state["spec"] = update["value"]

    def _pick_replica(self) -> dict:
        self._refresh()
        deadline = time.time() + 30
        while True:
            with self._lock:
                replicas = list(self._state["replicas"])
            if replicas:
                break
            if time.time() > deadline:
                raise RuntimeError(
                    f"no replicas for {self.app_name}/"
                    f"{self.deployment_name}"
                )
            time.sleep(0.05)
            self._refresh(force=True)
        # Model warmth beats locality: a replica already holding the
        # request's multiplexed model skips a load (reference: the
        # replica scheduler ranks multiplexed-model holders first).
        if self._model_id:
            warm = [
                r
                for r in replicas
                if self._model_id in (r.get("model_ids") or ())
            ]
            if warm:
                replicas = warm
        # Locality: prefer replicas on this node when any exist
        # (reference: pow_2 replica scheduler's locality-preferred
        # candidate set); pow-2 needs >=2 candidates to choose among.
        local_node = _local_node_id()
        if local_node is not None:
            local = [
                r for r in replicas if r.get("node_id") == local_node
            ]
            if local:
                replicas = local
        if len(replicas) == 1:
            return replicas[0]
        if _serve_config().serve_routing_policy == "pow2":
            # Legacy policy: power of two choices on this router's
            # in-flight REQUEST counts.
            a, b = random.sample(replicas, 2)
            with self._lock:
                na = self._ongoing.get(a["id"], 0)
                nb = self._ongoing.get(b["id"], 0)
            return a if na <= nb else b
        # Least outstanding tokens over the full candidate set
        # (replica counts are small; a full scan beats sampling noise).
        with self._lock:
            return pick_least_outstanding(
                replicas, self._outstanding_tokens
            )

    def _ongoing_sent(
        self, replica_id: Optional[str] = None, tokens: int = 0
    ) -> None:
        with self._lock:
            self._sent += 1
            if replica_id:
                self._ongoing[replica_id] = (
                    self._ongoing.get(replica_id, 0) + 1
                )
                if tokens > 0:
                    self._outstanding_tokens[replica_id] = (
                        self._outstanding_tokens.get(replica_id, 0)
                        + tokens
                    )
        self._ensure_reporter()

    def _ongoing_done(self, replica_id: Optional[str] = None) -> None:
        with self._lock:
            self._done += 1
            if replica_id and self._ongoing.get(replica_id, 0) > 0:
                self._ongoing[replica_id] -= 1

    def _tokens_done(
        self, replica_id: Optional[str], tokens: int
    ) -> None:
        """Release `tokens` of a replica's outstanding estimate,
        floored at zero (estimates are heuristic; a floor beats a
        slowly-accreting negative bias)."""
        if not replica_id or tokens <= 0:
            return
        with self._lock:
            remaining = (
                self._outstanding_tokens.get(replica_id, 0) - tokens
            )
            if remaining > 0:
                self._outstanding_tokens[replica_id] = remaining
            else:
                self._outstanding_tokens.pop(replica_id, None)

    def _prune_gone_locked(self) -> None:
        """Drop load accounting for replicas no longer in the
        membership (caller holds the lock)."""
        live = {r["id"] for r in self._state["replicas"]}
        for table in (self._ongoing, self._outstanding_tokens):
            for replica_id in list(table):
                if replica_id not in live:
                    del table[replica_id]

    def _ensure_reporter(self) -> None:
        """Push ongoing-load metrics to the controller for autoscaling
        (reference: autoscaling_state consumes handle metrics)."""
        with self._lock:
            if self._reporter is not None:
                return
            self._reporter = threading.Thread(
                target=self._report_loop, daemon=True
            )
            self._reporter.start()

    def _report_loop(self) -> None:
        try:
            while True:
                time.sleep(0.25)
                try:
                    controller = _controller()
                    with self._lock:
                        ongoing = self._sent - self._done
                    controller.report_metrics.remote(
                        self.app_name,
                        self.deployment_name,
                        self._handle_id,
                        float(max(0, ongoing)),
                    )
                except Exception:
                    # Transient controller hiccups (redeploys, races)
                    # must not kill autoscaling reporting for good.
                    continue
        finally:
            # If the thread ever exits (interpreter teardown), allow a
            # later send to restart it.
            with self._lock:
                self._reporter = None

    # -- calls ---------------------------------------------------------
    def _share_state_with(self, clone: "DeploymentHandle") -> None:
        # Share routing state so ongoing counts aggregate and the
        # long-poll listener is started once per handle family.
        clone.__dict__.update(
            {
                k: self.__dict__[k]
                for k in (
                    "_handle_id",
                    "_lock",
                    "_state",
                    "_ongoing",
                    "_outstanding_tokens",
                    "_batchers",
                    "_listener_box",
                )
            }
        )

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        clone = DeploymentHandle(
            self.app_name, self.deployment_name, name
        )
        self._share_state_with(clone)
        clone._method = name
        clone._model_id = self._model_id
        clone._request_id = self._request_id
        return clone

    def options(
        self,
        *,
        stream: bool = False,
        multiplexed_model_id: str = "",
        request_id: str = "",
    ) -> "DeploymentHandle":
        """`stream=True` makes remote() return a
        DeploymentResponseGenerator whose chunks arrive as the replica
        yields them (reference: handle.py
        DeploymentHandle.options(stream=True)).
        `multiplexed_model_id` tags requests with the model they need;
        the router prefers replicas already holding it and the replica
        exposes it via serve.get_multiplexed_model_id() (reference:
        handle.options(multiplexed_model_id=...)).
        `request_id` pins the next call's request id (the proxy
        propagates the client's ``x-request-id`` this way); by default
        each call mints its own."""
        clone = DeploymentHandle(
            self.app_name, self.deployment_name, self._method
        )
        self._share_state_with(clone)
        clone._stream = stream
        clone._model_id = multiplexed_model_id or self._model_id
        clone._request_id = request_id or self._request_id
        return clone

    def _request_ctx(self) -> dict:
        """Request context shipped with the replica call: id (minted
        here unless the proxy pinned one via options), deployment
        identity, the send timestamp the replica turns into queue
        wait, and the current span context so the replica's span
        nests under the caller's trace."""
        from ..util.tracing import inject_context

        from .observability import new_request_context

        return new_request_context(
            self.app_name,
            self.deployment_name,
            request_id=self._request_id or None,
            trace=inject_context(),
        )

    def remote(self, *args, **kwargs):
        from .observability import observe_routing

        self._refresh()
        with self._lock:
            batched = (
                self._state["spec"] or {}
            ).get("batched_methods", {}).get(
                self._method
            )
        if batched:
            with self._lock:
                batcher = self._batchers.get(self._method)
                if batcher is None:
                    batcher = _BatchQueue(self, self._method, batched)
                    self._batchers[self._method] = batcher
            if kwargs:
                raise TypeError(
                    "@serve.batch methods take positional args only"
                )
            return batcher.submit(args)
        t0 = time.perf_counter()
        replica = self._pick_replica()
        observe_routing(
            self.app_name,
            self.deployment_name,
            (time.perf_counter() - t0) * 1e3,
        )
        tokens = estimate_request_tokens(args, kwargs)
        self._slo_admit(replica, tokens)
        ctx = self._request_ctx()
        if self._stream:
            ref_gen = replica["actor"].handle_request_streaming.options(
                num_returns="streaming"
            ).remote(self._method, args, kwargs, self._model_id, ctx)
            self._ongoing_sent(replica["id"], tokens)
            return DeploymentResponseGenerator(
                ref_gen,
                self,
                replica["id"],
                actor=replica["actor"],
                request_id=str(ctx.get("request_id", "")),
                tokens=tokens,
            )
        ref = replica["actor"].handle_request.remote(
            self._method, args, kwargs, self._model_id, ctx
        )
        self._ongoing_sent(replica["id"], tokens)

        def waiter(timeout):
            import ray_tpu as rt

            try:
                return rt.get(ref, timeout=timeout)
            except BaseException as e:  # noqa: BLE001 — surfaced at
                return e  # .result()

        response = DeploymentResponse(waiter, self)
        response._replica_id = replica["id"]
        response._tokens = tokens
        return response

    def _slo_admit(self, replica: dict, tokens: int) -> None:
        """SLO admission control: `replica` is already the LEAST-
        loaded candidate, so its estimate over the threshold means
        every candidate is over — queueing this request would only
        deepen a queue that is already past the latency budget. Shed
        instead (the proxy turns this into 503 + Retry-After)."""
        cfg = _serve_config()
        if not cfg.serve_slo_admission_enabled:
            return
        threshold = cfg.serve_slo_queue_threshold_tokens
        if threshold <= 0:
            return
        with self._lock:
            load = self._outstanding_tokens.get(replica["id"], 0)
        if load >= threshold:
            raise DeploymentOverloaded(
                f"{self.app_name}/{self.deployment_name}: least-"
                f"loaded replica has ~{load} outstanding tokens "
                f"(threshold {threshold}); shedding {tokens}-token "
                "request"
            )

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self.app_name, self.deployment_name, self._method),
        )
