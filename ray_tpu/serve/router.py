"""DeploymentHandle + power-of-two-choices routing.

Reference: python/ray/serve/handle.py (DeploymentHandle /
DeploymentResponse) and _private/replica_scheduler/pow_2_scheduler.py:52
— pick two random replicas, send to the one with fewer ongoing
requests tracked by this router. Batched methods group concurrent
calls handle-side into one replica call (reference: serve/batching.py,
relocated to the router because replicas execute serially here).
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from .controller import CONTROLLER_NAME

_REPLICA_CACHE_TTL = 1.0


def _controller():
    import ray_tpu as rt

    return rt.get_actor(CONTROLLER_NAME, namespace="serve")


class DeploymentResponse:
    """Future for one request (reference: serve/handle.py
    DeploymentResponse.result())."""

    def __init__(self, waiter, router: "DeploymentHandle"):
        self._waiter = waiter  # callable(timeout) -> value
        self._router = router
        self._resolved = False
        self._value = None

    def result(self, timeout: Optional[float] = 30.0):
        if not self._resolved:
            try:
                self._value = self._waiter(timeout)
            finally:
                self._router._ongoing_done(
                    getattr(self, "_replica_id", None)
                )
            self._resolved = True
        if isinstance(self._value, BaseException):
            raise self._value
        return self._value


class _BatchQueue:
    """Handle-side batcher for @serve.batch methods."""

    def __init__(self, handle: "DeploymentHandle", method: str, cfg: dict):
        self._handle = handle
        self._method = method
        self._max = cfg["max_batch_size"]
        self._wait = cfg["batch_wait_timeout_s"]
        self._lock = threading.Lock()
        self._pending: List[dict] = []
        self._timer: Optional[threading.Timer] = None

    def submit(self, args: tuple) -> "DeploymentResponse":
        entry = {
            "args": args,
            "event": threading.Event(),
            "value": None,
        }
        flush_now = False
        with self._lock:
            self._pending.append(entry)
            if len(self._pending) >= self._max:
                flush_now = True
            elif self._timer is None:
                self._timer = threading.Timer(self._wait, self._flush)
                self._timer.daemon = True
                self._timer.start()
        if flush_now:
            self._flush()
        self._handle._ongoing_sent()

        def waiter(timeout):
            if not entry["event"].wait(timeout):
                raise TimeoutError(
                    f"batched call to {self._method} timed out"
                )
            return entry["value"]

        return DeploymentResponse(waiter, self._handle)

    def _flush(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            batch, self._pending = self._pending, []
        if not batch:
            return
        import ray_tpu as rt

        replica = self._handle._pick_replica()
        ref = replica["actor"].handle_batch.remote(
            self._method, [e["args"] for e in batch]
        )

        def deliver():
            try:
                values = rt.get(ref, timeout=60)
                if not isinstance(values, list) or len(values) != len(
                    batch
                ):
                    raise ValueError(
                        "@serve.batch method must return a list with "
                        "one output per input"
                    )
            except BaseException as e:  # noqa: BLE001 — forwarded
                values = [e] * len(batch)
            for entry, value in zip(batch, values):
                entry["value"] = value
                entry["event"].set()

        threading.Thread(target=deliver, daemon=True).start()


class DeploymentHandle:
    def __init__(
        self,
        app_name: str,
        deployment_name: str,
        method_name: str = "__call__",
    ):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._method = method_name
        self._handle_id = uuid.uuid4().hex[:8]
        self._lock = threading.Lock()
        self._replicas: List[dict] = []
        self._replicas_ts = 0.0
        self._spec: Optional[dict] = None
        self._ongoing: Dict[str, int] = {}  # replica_id -> in flight
        self._sent = 0
        self._done = 0
        self._batchers: Dict[str, _BatchQueue] = {}
        self._reporter: Optional[threading.Thread] = None

    # -- routing -------------------------------------------------------
    def _refresh(self, force: bool = False) -> None:
        now = time.time()
        with self._lock:
            fresh = (
                not force
                and self._replicas
                and now - self._replicas_ts < _REPLICA_CACHE_TTL
            )
        if fresh:
            return
        import ray_tpu as rt

        controller = _controller()
        replicas = rt.get(
            controller.get_replicas.remote(
                self.app_name, self.deployment_name
            ),
            timeout=30,
        )
        spec = rt.get(
            controller.get_deployment_spec.remote(
                self.app_name, self.deployment_name
            ),
            timeout=30,
        )
        with self._lock:
            self._replicas = replicas
            self._replicas_ts = now
            self._spec = spec

    def _pick_replica(self) -> dict:
        self._refresh()
        deadline = time.time() + 30
        while True:
            with self._lock:
                replicas = list(self._replicas)
            if replicas:
                break
            if time.time() > deadline:
                raise RuntimeError(
                    f"no replicas for {self.app_name}/"
                    f"{self.deployment_name}"
                )
            time.sleep(0.05)
            self._refresh(force=True)
        if len(replicas) == 1:
            return replicas[0]
        # Power of two choices on this router's in-flight counts.
        a, b = random.sample(replicas, 2)
        with self._lock:
            na = self._ongoing.get(a["id"], 0)
            nb = self._ongoing.get(b["id"], 0)
        return a if na <= nb else b

    def _ongoing_sent(self, replica_id: Optional[str] = None) -> None:
        with self._lock:
            self._sent += 1
            if replica_id:
                self._ongoing[replica_id] = (
                    self._ongoing.get(replica_id, 0) + 1
                )
        self._ensure_reporter()

    def _ongoing_done(self, replica_id: Optional[str] = None) -> None:
        with self._lock:
            self._done += 1
            if replica_id and self._ongoing.get(replica_id, 0) > 0:
                self._ongoing[replica_id] -= 1

    def _ensure_reporter(self) -> None:
        """Push ongoing-load metrics to the controller for autoscaling
        (reference: autoscaling_state consumes handle metrics)."""
        with self._lock:
            if self._reporter is not None:
                return
            self._reporter = threading.Thread(
                target=self._report_loop, daemon=True
            )
            self._reporter.start()

    def _report_loop(self) -> None:
        try:
            while True:
                time.sleep(0.25)
                try:
                    controller = _controller()
                    with self._lock:
                        ongoing = self._sent - self._done
                    controller.report_metrics.remote(
                        self.app_name,
                        self.deployment_name,
                        self._handle_id,
                        float(max(0, ongoing)),
                    )
                except Exception:
                    # Transient controller hiccups (redeploys, races)
                    # must not kill autoscaling reporting for good.
                    continue
        finally:
            # If the thread ever exits (interpreter teardown), allow a
            # later send to restart it.
            with self._lock:
                self._reporter = None

    # -- calls ---------------------------------------------------------
    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        clone = DeploymentHandle(
            self.app_name, self.deployment_name, name
        )
        # Share the routing state so ongoing counts aggregate.
        clone.__dict__.update(
            {
                k: self.__dict__[k]
                for k in (
                    "_handle_id",
                    "_lock",
                    "_replicas",
                    "_replicas_ts",
                    "_spec",
                    "_ongoing",
                    "_batchers",
                )
            }
        )
        clone._method = name
        return clone

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._refresh()
        with self._lock:
            batched = (self._spec or {}).get("batched_methods", {}).get(
                self._method
            )
        if batched:
            with self._lock:
                batcher = self._batchers.get(self._method)
                if batcher is None:
                    batcher = _BatchQueue(self, self._method, batched)
                    self._batchers[self._method] = batcher
            if kwargs:
                raise TypeError(
                    "@serve.batch methods take positional args only"
                )
            return batcher.submit(args)
        replica = self._pick_replica()
        ref = replica["actor"].handle_request.remote(
            self._method, args, kwargs
        )
        self._ongoing_sent(replica["id"])

        def waiter(timeout):
            import ray_tpu as rt

            try:
                return rt.get(ref, timeout=timeout)
            except BaseException as e:  # noqa: BLE001 — surfaced at
                return e  # .result()

        response = DeploymentResponse(waiter, self)
        response._replica_id = replica["id"]
        return response

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self.app_name, self.deployment_name, self._method),
        )
