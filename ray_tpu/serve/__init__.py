"""Model serving (reference: python/ray/serve)."""

from .api import (
    delete,
    get_app_handle,
    local_grpc_port,
    run,
    shutdown,
    proxy_ports,
    start,
    status,
    status_detail,
)
from .multiplex import get_multiplexed_model_id, multiplexed
from .observability import get_request_id
from .deployment import (
    Application,
    AutoscalingConfig,
    Deployment,
    batch,
    deployment,
)
from .proxy import Request
from .router import (
    DeploymentHandle,
    DeploymentOverloaded,
    DeploymentResponse,
)

__all__ = [
    "deployment",
    "multiplexed",
    "get_multiplexed_model_id",
    "local_grpc_port",
    "Deployment",
    "Application",
    "AutoscalingConfig",
    "batch",
    "run",
    "proxy_ports",
    "start",
    "status",
    "status_detail",
    "get_request_id",
    "delete",
    "shutdown",
    "get_app_handle",
    "DeploymentHandle",
    "DeploymentOverloaded",
    "DeploymentResponse",
    "Request",
]
