"""Model serving (reference: python/ray/serve)."""

from .api import (
    delete,
    get_app_handle,
    run,
    shutdown,
    proxy_ports,
    start,
    status,
)
from .deployment import (
    Application,
    AutoscalingConfig,
    Deployment,
    batch,
    deployment,
)
from .proxy import Request
from .router import DeploymentHandle, DeploymentResponse

__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "AutoscalingConfig",
    "batch",
    "run",
    "proxy_ports",
    "start",
    "status",
    "delete",
    "shutdown",
    "get_app_handle",
    "DeploymentHandle",
    "DeploymentResponse",
    "Request",
]
