"""Deployment definitions.

Reference: python/ray/serve/deployment.py — @serve.deployment wraps a
class into a Deployment; .bind(*args) produces an Application whose
arguments may themselves be bound deployments (model composition,
reference: serve/handle.py DeploymentHandle passed to the replica at
init).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class AutoscalingConfig:
    """(reference: serve/config.py AutoscalingConfig — scale on ongoing
    requests per replica)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 3.0


class Deployment:
    def __init__(
        self,
        cls: type,
        name: str,
        *,
        num_replicas: int = 1,
        ray_actor_options: Optional[dict] = None,
        autoscaling_config: Optional[AutoscalingConfig] = None,
        max_ongoing_requests: int = 8,
        version: str = "1",
    ):
        self._cls = cls
        self.name = name
        self.num_replicas = num_replicas
        self.ray_actor_options = ray_actor_options or {}
        self.autoscaling_config = autoscaling_config
        self.max_ongoing_requests = max_ongoing_requests
        self.version = version

    def options(self, **overrides) -> "Deployment":
        merged = {
            "num_replicas": self.num_replicas,
            "ray_actor_options": self.ray_actor_options,
            "autoscaling_config": self.autoscaling_config,
            "max_ongoing_requests": self.max_ongoing_requests,
            "version": self.version,
        }
        name = overrides.pop("name", self.name)
        merged.update(overrides)
        return Deployment(self._cls, name, **merged)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    @property
    def underlying(self) -> type:
        return self._cls


class Application:
    """A bound deployment graph rooted at the ingress (reference:
    serve/built_application.py)."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def flatten(self) -> List["Application"]:
        """All bound deployments, dependencies first."""
        seen: Dict[int, Application] = {}
        order: List[Application] = []

        def visit(app: "Application"):
            if id(app) in seen:
                return
            seen[id(app)] = app
            for arg in list(app.args) + list(app.kwargs.values()):
                if isinstance(arg, Application):
                    visit(arg)
            order.append(app)

        visit(self)
        return order


def deployment(
    cls: Optional[type] = None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    ray_actor_options: Optional[dict] = None,
    autoscaling_config: Optional[AutoscalingConfig | dict] = None,
    max_ongoing_requests: int = 8,
    version: str = "1",
):
    """@serve.deployment decorator (reference: serve/api.py:deployment)."""

    def wrap(target: type) -> Deployment:
        if isinstance(autoscaling_config, dict):
            autoscale = AutoscalingConfig(**autoscaling_config)
        else:
            autoscale = autoscaling_config
        return Deployment(
            target,
            name or target.__name__,
            num_replicas=num_replicas,
            ray_actor_options=ray_actor_options,
            autoscaling_config=autoscale,
            max_ongoing_requests=max_ongoing_requests,
            version=version,
        )

    if cls is not None:
        return wrap(cls)
    return wrap


def batch(
    _fn=None, *, max_batch_size: int = 8, batch_wait_timeout_s: float = 0.01
):
    """@serve.batch — marks a method as batched: the router groups
    concurrent calls and the method receives a list of inputs,
    returning a list of outputs (reference: serve/batching.py)."""

    def wrap(fn):
        fn.__rt_serve_batch__ = {
            "max_batch_size": max_batch_size,
            "batch_wait_timeout_s": batch_wait_timeout_s,
        }
        return fn

    if _fn is not None:
        return wrap(_fn)
    return wrap
