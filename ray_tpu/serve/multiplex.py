"""Model multiplexing: many models per deployment, LRU per replica.

Reference: python/ray/serve/multiplex.py (_ModelMultiplexWrapper) +
api.py:559 (@serve.multiplexed) + the router's model-aware replica
ranking — one deployment serves N models (multi-LoRA on TPU being the
canonical case), each replica holds at most `max_num_models_per_
replica` loaded, and the router prefers replicas that already hold a
request's model so loads amortize.

Flow:
    @serve.deployment
    class Multi:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str): return load(model_id)
        def __call__(self, request):
            model = self.get_model(serve.get_multiplexed_model_id())
            return model(request)

    handle.options(multiplexed_model_id="m1").remote(...)

The model id rides request metadata; the replica sets it into a
context variable around the call (get_multiplexed_model_id reads it),
and reports its loaded set to the controller, which pushes it to
routers over the existing long-poll channel.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

_model_id_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """Model id of the request being handled (reference:
    serve.get_multiplexed_model_id)."""
    return _model_id_ctx.get()


def _set_request_model_id(model_id: str):
    return _model_id_ctx.set(model_id)


class _ModelMultiplexWrapper:
    """Per-replica LRU of model_id -> loaded model (reference:
    multiplex.py _ModelMultiplexWrapper). Thread-safe: replicas run
    concurrent requests; a model loading twice concurrently is
    wasteful, so loads of the SAME id serialize on a per-id event."""

    def __init__(
        self,
        load_fn: Callable[[Any, str], Any],
        owner: Any,
        max_models: int,
        on_change: Optional[Callable[[List[str]], None]] = None,
    ):
        self._load_fn = load_fn
        self._owner = owner
        self._max = max(1, max_models)
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._loading: Dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self._on_change = on_change

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._models)

    def models(self) -> Dict[str, Any]:
        """Snapshot of the loaded {model_id: model} set (stable public
        accessor — e.g. LLMServer.engine_stats reads per-family engine
        health through it)."""
        with self._lock:
            return dict(self._models)

    def load(self, model_id: str) -> Any:
        while True:
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                pending = self._loading.get(model_id)
                if pending is None:
                    self._loading[model_id] = threading.Event()
                    break
            pending.wait(timeout=600)
        try:
            # The swap cost a cold-model request pays before its
            # handler runs — per-deployment histogram + flight-recorder
            # event, attributed to the request that triggered the load
            # (observability.current_request_context).
            from .observability import observe_model_load

            t0 = time.perf_counter()
            model = self._load_fn(self._owner, model_id)
            observe_model_load(
                model_id, (time.perf_counter() - t0) * 1e3
            )
            evicted = None
            with self._lock:
                if len(self._models) >= self._max:
                    _evicted_id, evicted = self._models.popitem(
                        last=False
                    )
                self._models[model_id] = model
                ids = list(self._models)
            # Teardown outside the lock; models with a close/del hook
            # release accelerator memory promptly (reference: the
            # wrapper awaits __del__ on eviction).
            if evicted is not None:
                for hook in ("__serve_unload__", "close"):
                    fn = getattr(evicted, hook, None)
                    if callable(fn):
                        try:
                            fn()
                        except Exception:
                            pass
                        break
            if self._on_change is not None:
                try:
                    self._on_change(ids)
                except Exception:
                    pass
            return model
        finally:
            with self._lock:
                event = self._loading.pop(model_id, None)
            if event is not None:
                event.set()


class _MultiplexedMethod:
    """Descriptor produced by @serve.multiplexed: binds one wrapper
    per instance (per replica process)."""

    def __init__(self, func: Callable, max_models: int):
        self._func = func
        self.max_num_models_per_replica = max_models
        self._attr = f"__serve_multiplex_{func.__name__}"

    def __set_name__(self, owner, name):
        self._name = name

    def __get__(self, instance, owner=None):
        if instance is None:
            return self
        wrapper = getattr(instance, self._attr, None)
        if wrapper is None:
            on_change = getattr(
                instance, "__serve_multiplex_report__", None
            )
            wrapper = _ModelMultiplexWrapper(
                self._func, instance, self.max_num_models_per_replica,
                on_change=on_change,
            )
            setattr(instance, self._attr, wrapper)
        return wrapper.load


def multiplexed(
    func: Optional[Callable] = None,
    *,
    max_num_models_per_replica: int = 3,
):
    """Mark a model-loader method for multiplexing (reference:
    serve/api.py:559 @serve.multiplexed)."""

    def wrap(f: Callable) -> _MultiplexedMethod:
        return _MultiplexedMethod(f, max_num_models_per_replica)

    if func is not None:
        return wrap(func)
    return wrap
