"""HTTP ingress proxy.

Reference: python/ray/serve/_private/proxy.py:1135 — a per-node proxy
actor terminates HTTP and routes by path prefix to the application's
ingress deployment; serve.start() places one proxy on EVERY alive
node (reference: proxy_state.py per-node proxies), and each proxy's
routers prefer replicas on their own node. The reference runs
uvicorn/starlette (ASGI); here a stdlib ThreadingHTTPServer thread
inside the proxy actor serves the same role, and the request surface
handed to the ingress __call__ is a small Request object
(method/path/query/headers/body/json). Route changes arrive by
controller long-poll push (reference: long_poll.py), and generator
ingresses stream out as chunked transfer-encoding — token N is on the
wire while the replica computes token N+1. Admission control bounds
in-flight requests (immediate 503 + Retry-After past the cap) and
live connections (raw 503 before a handler thread spawns);
/-/healthz reports both shed counters.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse


class Request:
    """What the ingress deployment's __call__ receives."""

    def __init__(
        self,
        method: str,
        path: str,
        query_params: Dict[str, str],
        headers: Dict[str, str],
        body: bytes,
    ):
        self.method = method
        self.path = path
        self.query_params = query_params
        self.headers = headers
        self.body = body

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body)

    def text(self) -> str:
        return self.body.decode()


class Proxy:
    """Proxy actor body: serves HTTP on `port`, routes to ingress
    handles via longest-prefix match."""

    def __init__(
        self,
        port: int,
        fallback_ephemeral: bool = True,
        host: str = "127.0.0.1",
        grpc_port: int = None,
        max_concurrent_requests: int = 256,
        max_connections: int = 1024,
    ):
        self.port = port
        self._routes: Dict[str, Tuple[str, str]] = {}
        self._routes_ts = 0.0
        self._handles: Dict[Tuple[str, str], Any] = {}
        # Ingress admission control (reference: proxy.py limits in-
        # flight requests per proxy and uvicorn bounds connections;
        # an unbounded thread-per-connection server melts under a
        # connection flood). Saturated REQUESTS shed with 503 +
        # Retry-After (the client can act on it); saturated
        # CONNECTIONS get a raw 503 and a close before a handler
        # thread is ever spawned.
        self._request_slots = threading.BoundedSemaphore(
            max_concurrent_requests
        )
        self._conn_count = 0
        self._conn_lock = threading.Lock()
        self._max_connections = max_connections
        self.shed_requests = 0  # observability: /-/healthz surfaces it
        self.shed_connections = 0
        # SLO admission sheds (router raised DeploymentOverloaded:
        # every candidate replica's outstanding-token estimate is over
        # threshold — see serve/router.py).
        self.shed_slo = 0
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _serve(self):
                # Non-blocking admission: a saturated proxy answers
                # immediately instead of queueing unboundedly (a slow
                # replica would otherwise stack threads until OOM).
                if not proxy._request_slots.acquire(blocking=False):
                    with proxy._conn_lock:
                        proxy.shed_requests += 1
                    payload = json.dumps(
                        {"error": "proxy at max_concurrent_requests"}
                    ).encode()
                    self.send_response(503)
                    self.send_header("Retry-After", "1")
                    # Close rather than drain: the unread request body
                    # would otherwise desynchronize this keep-alive
                    # connection (next "request line" = body bytes),
                    # and draining would let a slow client occupy the
                    # very proxy that is shedding load.
                    self.send_header("Connection", "close")
                    self.close_connection = True
                    self.send_header(
                        "Content-Type", "application/json"
                    )
                    self.send_header(
                        "Content-Length", str(len(payload))
                    )
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                try:
                    try:
                        result = proxy._dispatch(self)
                    except Exception as e:  # noqa: BLE001 — 500
                        result = (
                            500,
                            json.dumps({"error": repr(e)}).encode(),
                            "application/json",
                        )
                    if result is None:
                        return  # response already streamed
                    status, payload, ctype = result
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    request_id = getattr(
                        self, "_rt_request_id", None
                    )
                    if request_id:
                        self.send_header("x-request-id", request_id)
                    retry_after = getattr(
                        self, "_rt_retry_after", None
                    )
                    if retry_after:
                        self.send_header("Retry-After", retry_after)
                    self.send_header(
                        "Content-Length", str(len(payload))
                    )
                    self.end_headers()
                    self.wfile.write(payload)
                finally:
                    proxy._request_slots.release()

            do_GET = do_POST = do_PUT = do_DELETE = _serve

        class BoundedThreadingHTTPServer(ThreadingHTTPServer):
            # Connection cap enforced BEFORE a handler thread spawns:
            # over the cap, write a minimal 503 and close. Keep-alive
            # connections hold a slot for their lifetime (like
            # uvicorn's --limit-concurrency), so the cap bounds proxy
            # thread count.
            def process_request(self, request, client_address):
                with proxy._conn_lock:
                    if proxy._conn_count >= proxy._max_connections:
                        proxy.shed_connections += 1
                        over = True
                    else:
                        proxy._conn_count += 1
                        over = False
                if over:
                    try:
                        request.sendall(
                            b"HTTP/1.1 503 Service Unavailable\r\n"
                            b"Connection: close\r\n"
                            b"Retry-After: 1\r\n"
                            b"Content-Length: 0\r\n\r\n"
                        )
                    except OSError:
                        pass
                    # Close via the BASE implementation: this
                    # connection never incremented the count, so it
                    # must not flow through the decrementing override.
                    ThreadingHTTPServer.shutdown_request(self, request)
                    return
                super().process_request(request, client_address)

            def shutdown_request(self, request):
                # Every admitted connection's close path (handler
                # thread finally, spawn-failure handle_error) lands
                # here exactly once.
                with proxy._conn_lock:
                    if proxy._conn_count > 0:
                        proxy._conn_count -= 1
                super().shutdown_request(request)

        import errno

        try:
            self._server = BoundedThreadingHTTPServer(
                (host, port), Handler
            )
        except OSError as e:
            if not fallback_ephemeral or e.errno != errno.EADDRINUSE:
                raise  # real bind failures must surface to the user
            # In-box multi-daemon clusters share one host: per-node
            # proxies can't all bind the same port there, so extras
            # take an ephemeral one (real multi-host nodes each bind
            # the configured port).
            self._server = BoundedThreadingHTTPServer(
                (host, 0), Handler
            )
        self.port = self._server.server_address[1]  # resolve port=0
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        self._listener = threading.Thread(
            target=self._routes_listen_loop, daemon=True,
            name="serve-proxy-longpoll",
        )
        self._listener.start()
        # Optional gRPC ingress on the same proxy (reference:
        # proxy.py:431 gRPCProxy lives beside the HTTP proxy); routes
        # by `application` call metadata.
        self._grpc = None
        self.grpc_port = None
        if grpc_port is not None:
            from .grpc_ingress import GrpcIngress

            try:
                self._grpc = GrpcIngress(
                    grpc_port, self._grpc_handle_for,
                    self._grpc_app_names, host=host,
                )
            except OSError:
                if not fallback_ephemeral:
                    raise
                self._grpc = GrpcIngress(
                    0, self._grpc_handle_for,
                    self._grpc_app_names, host=host,
                )
            self.grpc_port = self._grpc.port

    # -- gRPC routing --------------------------------------------------
    def _grpc_handle_for(self, app: str):
        from .router import DeploymentHandle

        self._refresh_routes()
        targets = {
            a: (a, ingress)
            for _prefix, (a, ingress) in self._routes.items()
        }
        if app not in targets:
            self._refresh_routes(force=True)
            targets = {
                a: (a, ingress)
                for _prefix, (a, ingress) in self._routes.items()
            }
        key = targets.get(app)
        if key is None:
            return None
        if key not in self._handles:
            self._handles[key] = DeploymentHandle(*key)
        return self._handles[key]

    def _grpc_app_names(self) -> list:
        self._refresh_routes(force=True)
        return sorted({a for (a, _d) in self._routes.values()})

    # -- routing -------------------------------------------------------
    def _refresh_routes(self, force: bool = False) -> None:
        import ray_tpu as rt

        from .controller import CONTROLLER_NAME

        if self._routes_ts and not force:
            return
        controller = rt.get_actor(CONTROLLER_NAME, namespace="serve")
        self._routes = rt.get(
            controller.get_routes.remote(), timeout=30
        )
        self._routes_ts = time.time()

    def _routes_listen_loop(self) -> None:
        """Route-table push (reference: proxy long-polls route_table
        through long_poll.py)."""
        import ray_tpu as rt

        from .controller import CONTROLLER_NAME

        keys = {"routes": 0}
        while True:
            try:
                controller = rt.get_actor(
                    CONTROLLER_NAME, namespace="serve"
                )
                changed = rt.get(
                    controller.listen_for_change.remote(dict(keys)),
                    timeout=60,
                )
            except Exception:
                time.sleep(0.2)
                continue
            if not changed:
                continue
            update = changed.get("routes")
            if update is not None:
                keys["routes"] = update["snapshot_id"]
                self._routes = update["value"] or {}
                self._routes_ts = time.time()

    def _match(self, path: str):
        best = None
        for prefix, target in self._routes.items():
            if path == prefix or path.startswith(
                prefix.rstrip("/") + "/"
            ) or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best

    def _dispatch(self, handler) -> Tuple[int, bytes, str]:
        # The Handler instance persists across keep-alive requests:
        # clear per-request state up front so no response (healthz
        # included) can echo a PREVIOUS request's id or Retry-After.
        handler._rt_request_id = None
        handler._rt_retry_after = None
        parsed = urlparse(handler.path)
        if parsed.path == "/-/healthz":
            return self._healthz(handler)
        return self._dispatch_observed(handler, parsed)

    def _healthz(self, handler) -> Tuple[int, bytes, str]:
        # Drain any body so the keep-alive stream stays in sync.
        length = int(handler.headers.get("Content-Length") or 0)
        if length:
            handler.rfile.read(length)
        return (
            200,
            json.dumps({
                "status": "ok",
                "connections": self._conn_count,
                "shed_requests": self.shed_requests,
                "shed_connections": self.shed_connections,
                "shed_slo": self.shed_slo,
            }).encode(),
            "application/json",
        )

    def _dispatch_observed(self, handler, parsed):
        """Route + call the ingress, wrapped in the request-path
        observability layer: a request id (client ``x-request-id``
        honored, minted otherwise) that propagates router -> replica
        -> multiplex and returns as a response header, an ingress
        span, and per-deployment HTTP latency/status metrics."""
        from ..util.tracing import span

        from .observability import (
            REQUEST_ID_HEADER,
            new_request_id,
            observe_http,
        )

        request_id = (
            handler.headers.get(REQUEST_ID_HEADER) or new_request_id()
        )
        # Set for EVERY request, before routing: the handler instance
        # persists across keep-alive requests, so a late assignment
        # would echo request A's id on request B's 404/error response.
        handler._rt_request_id = request_id
        t0 = time.perf_counter()
        # Filled by _route_request with the route that actually
        # served the request — re-matching in the finally would both
        # rescan the table and misattribute across a mid-request
        # route-table refresh.
        target = {"app": "", "deployment": ""}
        status = 500
        try:
            with span(
                "serve.http",
                request_id=request_id,
                path=parsed.path,
            ):
                result = self._route_request(
                    handler, parsed, request_id, target
                )
            if result is None:
                # Streamed: the 200 header is already on the wire.
                status = 200
                return None
            status, payload, ctype = result
            return status, payload, ctype
        except Exception:
            status = 500
            raise
        finally:
            observe_http(
                target["app"],
                target["deployment"],
                parsed.path,
                status,
                (time.perf_counter() - t0) * 1e3,
                request_id,
            )

    def _route_request(self, handler, parsed, request_id, target):
        from .router import DeploymentHandle, DeploymentOverloaded

        self._refresh_routes()
        match = self._match(parsed.path)
        if match is None:
            self._refresh_routes(force=True)
            match = self._match(parsed.path)
        if match is None:
            return (
                404,
                json.dumps({"error": "no route"}).encode(),
                "application/json",
            )
        prefix, (app, ingress) = match
        target["app"], target["deployment"] = app, ingress
        key = (app, ingress)
        if key not in self._handles:
            self._handles[key] = DeploymentHandle(app, ingress)
        length = int(handler.headers.get("Content-Length") or 0)
        body = handler.rfile.read(length) if length else b""
        request = Request(
            method=handler.command,
            path=parsed.path[len(prefix.rstrip("/")) :] or "/",
            query_params={
                k: v[0] for k, v in parse_qs(parsed.query).items()
            },
            headers=dict(handler.headers.items()),
            body=body,
        )
        handle = self._handles[key]
        handle._refresh()
        # Reference header: requests carry the model they need and the
        # router prefers replicas already holding it (multiplex.py).
        model_id = handler.headers.get(
            "serve_multiplexed_model_id", ""
        )
        with handle._lock:
            streaming = bool(
                (handle._state["spec"] or {}).get("ingress_streaming")
            )
        try:
            if streaming:
                chunks = handle.options(
                    stream=True,
                    multiplexed_model_id=model_id,
                    request_id=request_id,
                ).remote(request)
                self._stream_response(handler, chunks)
                return None
            handle = handle.options(
                multiplexed_model_id=model_id, request_id=request_id
            )
            response = handle.remote(request)
        except DeploymentOverloaded as e:
            # SLO admission shed: every candidate replica's queue is
            # already past the latency budget — a fast 503 the client
            # can back off on beats joining a queue whose TTFT has
            # collapsed (the raise happens BEFORE any streaming
            # header, so the connection stays clean).
            with self._conn_lock:
                self.shed_slo += 1
            retry_after = max(1, int(round(e.retry_after_s)))
            handler._rt_retry_after = str(retry_after)
            return (
                503,
                json.dumps({
                    "error": str(e),
                    "retry_after_s": retry_after,
                }).encode(),
                "application/json",
            )
        value = response.result(timeout=60)
        if isinstance(value, bytes):
            return 200, value, "application/octet-stream"
        if isinstance(value, str):
            return 200, value.encode(), "text/plain"
        return (
            200,
            json.dumps(value, default=str).encode(),
            "application/json",
        )

    def _stream_response(self, handler, chunks) -> None:
        """Chunked transfer-encoding: each replica yield goes on the
        wire immediately (reference: proxy.py streaming ASGI
        responses for generator deployments — LLM token output)."""
        handler.send_response(200)
        handler.send_header("Content-Type", "text/plain; charset=utf-8")
        # Streaming clients need the id MOST (runbook: grep a slow
        # stream's id into the flight-recorder rings).
        request_id = getattr(handler, "_rt_request_id", None)
        if request_id:
            handler.send_header("x-request-id", request_id)
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()
        # Once the 200 header is out, NOTHING may escape this method:
        # a propagated exception would make the outer handler write a
        # second (500) response onto the same keep-alive connection,
        # desynchronizing the next request. The 0-length terminator is
        # written ONLY on clean completion — a replica error mid-stream
        # aborts the socket so the client observes a truncated chunked
        # body (a detectable failure) instead of a well-formed 200 with
        # silently missing content.
        clean = False
        try:
            try:
                for chunk in chunks:
                    data = (
                        chunk
                        if isinstance(chunk, bytes)
                        else str(chunk).encode()
                    )
                    if not data:
                        continue
                    handler.wfile.write(
                        f"{len(data):X}\r\n".encode() + data + b"\r\n"
                    )
                    handler.wfile.flush()
                clean = True
            finally:
                # Releases the router's ongoing-count slot even when
                # the client disconnected mid-stream.
                close = getattr(chunks, "close", None)
                if close is not None:
                    close()
                if clean:
                    handler.wfile.write(b"0\r\n\r\n")
                else:
                    handler.close_connection = True
                    try:
                        handler.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
        except Exception:
            handler.close_connection = True

    def ready(self) -> int:
        return self.port

    def grpc_ready(self):
        return self.grpc_port

    def stop(self) -> bool:
        if self._grpc is not None:
            self._grpc.stop()
        self._server.shutdown()
        return True
