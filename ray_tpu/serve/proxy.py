"""HTTP ingress proxy.

Reference: python/ray/serve/_private/proxy.py:1135 — a per-node proxy
actor terminates HTTP and routes by path prefix to the application's
ingress deployment. The reference runs uvicorn/starlette (ASGI); here
a stdlib ThreadingHTTPServer thread inside the proxy actor serves the
same role, and the request surface handed to the ingress __call__ is a
small Request object (method/path/query/headers/body/json).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse


class Request:
    """What the ingress deployment's __call__ receives."""

    def __init__(
        self,
        method: str,
        path: str,
        query_params: Dict[str, str],
        headers: Dict[str, str],
        body: bytes,
    ):
        self.method = method
        self.path = path
        self.query_params = query_params
        self.headers = headers
        self.body = body

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body)

    def text(self) -> str:
        return self.body.decode()


class Proxy:
    """Proxy actor body: serves HTTP on `port`, routes to ingress
    handles via longest-prefix match."""

    def __init__(self, port: int):
        self.port = port
        self._routes: Dict[str, Tuple[str, str]] = {}
        self._routes_ts = 0.0
        self._handles: Dict[Tuple[str, str], Any] = {}
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _serve(self):
                try:
                    status, payload, ctype = proxy._dispatch(self)
                except Exception as e:  # noqa: BLE001 — 500 surface
                    status = 500
                    payload = json.dumps({"error": repr(e)}).encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = do_PUT = do_DELETE = _serve

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    # -- routing -------------------------------------------------------
    def _refresh_routes(self, force: bool = False) -> None:
        import ray_tpu as rt

        from .controller import CONTROLLER_NAME

        if not force and time.time() - self._routes_ts < 2.0:
            return
        controller = rt.get_actor(CONTROLLER_NAME, namespace="serve")
        self._routes = rt.get(
            controller.get_routes.remote(), timeout=30
        )
        self._routes_ts = time.time()

    def _match(self, path: str):
        best = None
        for prefix, target in self._routes.items():
            if path == prefix or path.startswith(
                prefix.rstrip("/") + "/"
            ) or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best

    def _dispatch(self, handler) -> Tuple[int, bytes, str]:
        from .router import DeploymentHandle

        parsed = urlparse(handler.path)
        self._refresh_routes()
        match = self._match(parsed.path)
        if match is None:
            self._refresh_routes(force=True)
            match = self._match(parsed.path)
        if match is None:
            return (
                404,
                json.dumps({"error": "no route"}).encode(),
                "application/json",
            )
        prefix, (app, ingress) = match
        key = (app, ingress)
        if key not in self._handles:
            self._handles[key] = DeploymentHandle(app, ingress)
        length = int(handler.headers.get("Content-Length") or 0)
        body = handler.rfile.read(length) if length else b""
        request = Request(
            method=handler.command,
            path=parsed.path[len(prefix.rstrip("/")) :] or "/",
            query_params={
                k: v[0] for k, v in parse_qs(parsed.query).items()
            },
            headers=dict(handler.headers.items()),
            body=body,
        )
        value = self._handles[key].remote(request).result(timeout=60)
        if isinstance(value, bytes):
            return 200, value, "application/octet-stream"
        if isinstance(value, str):
            return 200, value.encode(), "text/plain"
        return (
            200,
            json.dumps(value, default=str).encode(),
            "application/json",
        )

    def ready(self) -> int:
        return self.port

    def stop(self) -> bool:
        self._server.shutdown()
        return True
