"""Pipeline schedules for MPMD stage gangs (1F1B + interleaved).

`parallel/pipeline.py` keeps the whole pipeline inside one jitted SPMD
program (GPipe over `lax.ppermute`). This module is the OTHER half of
the pipeline story — the multi-program mode the PAPERS.md MPMD paper
argues for: each stage is its own process/gang running its own jitted
fwd/bwd program, activations hop stages over runtime channels, and the
per-stage op ORDER comes from a schedule built here ahead of time.

Everything in this module is pure Python over op tuples — no jax, no
runtime — so schedules are unit-testable (stash bounds, deadlock
freedom) and replayable against measured per-op costs
(`simulate_schedule`), which is how pipebench turns a 1-core CPU run
into a defensible pipeline-efficiency number.

An op is a tuple ``(kind, chunk, mb)``:
  kind   "F" (forward) or "B" (backward)
  chunk  virtual-stage index in [0, n_stages * chunks_per_stage);
         chunk ``c`` lives on physical stage ``c % n_stages``
         (Megatron-style interleaved placement; with
         chunks_per_stage=1, chunk == stage).
  mb     microbatch index in [0, num_microbatches).

Dependencies: F(c, mb) needs F(c-1, mb); B(c, mb) needs B(c+1, mb)
(B of the last chunk needs its own F — the stash). A schedule is a
list of per-PHYSICAL-stage op lists executed strictly in order;
activations/grad records travel on one FIFO edge per (chunk boundary,
direction), so record order on every edge is monotonic in mb by
construction and a receiver can never see a record it is not the
schedule-mandated consumer of.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

Op = Tuple[str, int, int]  # (kind, chunk, mb)


def one_f_one_b(n_stages: int, num_microbatches: int) -> List[List[Op]]:
    """Per-stage op lists for the classic 1F1B (PipeDream-flush)
    schedule: stage s warms up with ``min(n-1-s, m)`` forwards, then
    alternates one-forward-one-backward in steady state, then drains
    the remaining backwards. Stash depth is warmup+1 <= n_stages —
    the whole point vs GPipe's O(num_microbatches) stash."""
    n, m = int(n_stages), int(num_microbatches)
    if n < 1 or m < 1:
        raise ValueError(f"need n_stages>=1, num_microbatches>=1 "
                         f"(got {n}, {m})")
    schedules: List[List[Op]] = []
    for s in range(n):
        warm = min(n - 1 - s, m)
        ops: List[Op] = [("F", s, i) for i in range(warm)]
        f = warm
        for b in range(m - warm):
            ops.append(("F", s, f))
            f += 1
            ops.append(("B", s, b))
        for b in range(m - warm, m):
            ops.append(("B", s, b))
        schedules.append(ops)
    return schedules


def interleaved_1f1b(
    n_stages: int,
    num_microbatches: int,
    chunks_per_stage: int,
) -> List[List[Op]]:
    """Per-physical-stage op lists for the interleaved (virtual-stage)
    schedule: the model is split into ``n_stages * chunks_per_stage``
    chunks, chunk c on stage c % n_stages, and each physical stage
    merges its chunks' 1F1B streams greedily (earliest-ready op first,
    per-chunk order preserved). Shrinks the warmup/cooldown bubble by
    ~1/chunks_per_stage at the cost of more boundary hops.

    chunks_per_stage=1 degenerates to exactly `one_f_one_b`.
    """
    n, m, v = int(n_stages), int(num_microbatches), int(chunks_per_stage)
    if v < 1:
        raise ValueError(f"chunks_per_stage must be >= 1 (got {v})")
    if v == 1:
        return one_f_one_b(n, m)
    V = n * v
    virtual = one_f_one_b(V, m)  # chunk c's own op order
    cursor = [0] * V
    # (kind, chunk, mb) -> completion tick of the unit-cost greedy
    # simulation below; presence = scheduled (list-schedule validity
    # only needs deps to appear earlier in some stage's list).
    done: Dict[Op, float] = {}
    free = [0.0] * n
    schedules: List[List[Op]] = [[] for _ in range(n)]
    remaining = V * len(virtual[0])

    def ready_at(op: Op) -> Optional[float]:
        kind, c, mb = op
        if kind == "F":
            dep = ("F", c - 1, mb) if c > 0 else None
        else:
            dep = ("B", c + 1, mb) if c < V - 1 else ("F", c, mb)
        if dep is None:
            return 0.0
        return done.get(dep)

    while remaining:
        progressed = False
        # Offer the least-loaded stage first so the merge stays fair.
        for s in sorted(range(n), key=lambda i: free[i]):
            best: Optional[Tuple[float, int, Op]] = None
            for c in range(s, V, n):
                if cursor[c] >= len(virtual[c]):
                    continue
                op = virtual[c][cursor[c]]
                at = ready_at(op)
                if at is None:
                    continue
                key = (at, c)
                if best is None or key < (best[0], best[1]):
                    best = (at, c, op)
            if best is None:
                continue
            at, c, op = best
            start = max(free[s], at)
            done[op] = start + 1.0
            free[s] = start + 1.0
            cursor[c] += 1
            schedules[s].append(op)
            remaining -= 1
            progressed = True
        if not progressed:
            raise RuntimeError(
                "interleaved schedule construction deadlocked "
                f"(n={n}, m={m}, v={v}) — this is a bug"
            )
    return schedules


def max_stash_depth(ops: Sequence[Op]) -> int:
    """Peak number of stashed forward activations one stage's op list
    holds (every F stashes its input until the matching B retires it).
    The 1F1B invariant: <= n_stages per chunk."""
    live = 0
    peak = 0
    for kind, _c, _mb in ops:
        if kind == "F":
            live += 1
            peak = max(peak, live)
        else:
            live -= 1
    return peak


def validate_schedule(
    schedules: Sequence[Sequence[Op]],
    n_stages: int,
    num_microbatches: int,
    chunks_per_stage: int = 1,
    channel_depth: Optional[int] = None,
) -> None:
    """Raise if the per-stage op lists are not a complete, deadlock-
    free execution of the pipeline: every (F, B) x chunk x mb op
    appears exactly once on its owning stage, per-chunk mb order is
    FIFO in both directions, and in-order execution of the lists never
    blocks on an op no earlier list position produces. Used by tests
    AND by the driver at build time — a malformed schedule must die at
    construction, not hang the gang.

    With ``channel_depth`` the check additionally models BOUNDED
    edges: a send blocks while its edge holds `depth` unconsumed
    records (exactly the runtime's ring-capacity backpressure). For
    fixed op lists over blocking FIFO edges, deadlock is
    timing-independent (a Kahn network), so this bounded execution
    decides it exactly — an interleaved schedule too deep for the
    configured depth dies HERE, not as an all-stages hang at
    hop-timeout."""
    n, m, v = int(n_stages), int(num_microbatches), int(chunks_per_stage)
    V = n * v
    want = {
        (kind, c, mb)
        for kind in ("F", "B")
        for c in range(V)
        for mb in range(m)
    }
    seen = set()
    for s, ops in enumerate(schedules):
        last_mb: Dict[Tuple[str, int], int] = {}
        for op in ops:
            kind, c, mb = op
            if c % n != s:
                raise ValueError(f"stage {s} scheduled foreign {op}")
            if op in seen:
                raise ValueError(f"duplicate op {op}")
            seen.add(op)
            prev = last_mb.get((kind, c), -1)
            if mb <= prev:
                raise ValueError(
                    f"stage {s} {kind} chunk {c}: mb {mb} after {prev} "
                    "(edge FIFO order violated)"
                )
            last_mb[(kind, c)] = mb
    if seen != want:
        missing = sorted(want - seen)[:4]
        raise ValueError(f"incomplete schedule; missing {missing}...")
    # In-order execution must make progress at every scan: classic
    # list-schedule deadlock check, with optional bounded edges.
    # Each op is two phases matching the runtime: (recv input,
    # compute) then (send output — blocks while the edge is full).
    depth: Optional[int] = None
    if channel_depth is not None:
        if channel_depth != int(channel_depth):
            raise ValueError(
                f"channel_depth must be integral (got {channel_depth})"
            )
        depth = int(channel_depth)
        if depth < 1:
            raise ValueError(
                f"channel_depth must be >= 1 (got {depth})"
            )
    # edge key: (boundary chunk index, direction) -> records in flight
    in_flight: Dict[Tuple[int, str], int] = {}

    def op_io(op: Op):
        """(recv_edge | None, send_edge | None) for an op."""
        kind, c, mb = op
        if kind == "F":
            recv = (c - 1, "fwd") if c > 0 else None
            send = (c, "fwd") if c < V - 1 else None
        else:
            recv = (c, "grad") if c < V - 1 else None
            send = (c - 1, "grad") if c > 0 else None
        return recv, send

    cursor = [0] * len(schedules)
    pending_send: List[Optional[Tuple[int, str]]] = [None] * len(
        schedules
    )
    done: set = set()
    total = sum(len(ops) for ops in schedules)
    completed = 0
    while completed < total:
        progressed = False
        for s, ops in enumerate(schedules):
            while cursor[s] < len(ops):
                op = ops[cursor[s]]
                kind, c, mb = op
                if pending_send[s] is not None:
                    # Mid-op: computed, blocked on a full edge.
                    edge = pending_send[s]
                    if depth is not None and in_flight.get(
                        edge, 0
                    ) >= depth:
                        break
                    in_flight[edge] = in_flight.get(edge, 0) + 1
                    pending_send[s] = None
                    done.add(op)
                    cursor[s] += 1
                    completed += 1
                    progressed = True
                    continue
                if kind == "F":
                    dep = ("F", c - 1, mb) if c > 0 else None
                else:
                    dep = ("B", c + 1, mb) if c < V - 1 else ("F", c, mb)
                if dep is not None and dep not in done:
                    break
                recv, send = op_io(op)
                if recv is not None:
                    # The dep's completion guarantees the record was
                    # delivered (dep in done covers its send phase).
                    in_flight[recv] = in_flight.get(recv, 0) - 1
                if send is not None and depth is not None and \
                        in_flight.get(send, 0) >= depth:
                    pending_send[s] = send
                    progressed = True  # the recv freed edge space
                    break
                if send is not None:
                    in_flight[send] = in_flight.get(send, 0) + 1
                done.add(op)
                cursor[s] += 1
                completed += 1
                progressed = True
        if not progressed:
            stuck = [
                (s, schedules[s][cursor[s]])
                for s in range(len(schedules))
                if cursor[s] < len(schedules[s])
            ]
            hint = (
                f" under channel_depth={depth} — raise "
                "pipeline_channel_depth or lower chunks_per_stage"
                if depth is not None
                else ""
            )
            raise ValueError(
                f"schedule deadlocks at {stuck[:4]}{hint}"
            )


def theoretical_efficiency(
    n_stages: int, num_microbatches: int, chunks_per_stage: int = 1
) -> float:
    """The bubble bound: fraction of each stage's ideal wall spent
    computing — m / (m + (n-1)/v) with balanced stages (the classic
    m/(m+n-1) at v=1; interleaving shrinks the fill/drain ramp by
    1/v)."""
    n, m, v = int(n_stages), int(num_microbatches), int(chunks_per_stage)
    return m / (m + (n - 1) / v)


def simulate_schedule(
    schedules: Sequence[Sequence[Op]],
    op_cost_s,
    hop_cost_s: float = 0.0,
) -> dict:
    """Replay per-stage op lists as a discrete-event simulation with
    each stage on its own executor: op start = max(stage free, inputs
    ready + hop), strictly in list order. `op_cost_s(kind, chunk, mb)`
    supplies each op's duration (pipebench feeds MEASURED per-op times
    from the real multi-stage run, so the result is a measurement-
    driven account of what the schedule costs when stages do not
    time-share a core — the honest pipeline-efficiency number a
    1-core CI box can produce, committed alongside the raw wall
    numbers it was derived from).

    Returns {wall_s, busy_s (per stage), idle_s (per stage),
    efficiency} where efficiency = total busy / (n_stages * wall) —
    directly comparable to `theoretical_efficiency`.
    """
    n = len(schedules)
    cursor = [0] * n
    free = [0.0] * n
    busy = [0.0] * n
    done: Dict[Op, float] = {}
    total = sum(len(ops) for ops in schedules)
    V = max((c for ops in schedules for _k, c, _m in ops), default=0) + 1
    completed = 0
    while completed < total:
        progressed = False
        for s in range(n):
            while cursor[s] < len(schedules[s]):
                op = schedules[s][cursor[s]]
                kind, c, mb = op
                if kind == "F":
                    dep = ("F", c - 1, mb) if c > 0 else None
                else:
                    dep = ("B", c + 1, mb) if c < V - 1 else ("F", c, mb)
                ready = 0.0
                if dep is not None:
                    if dep not in done:
                        break
                    ready = done[dep]
                    # Cross-stage deps pay the channel hop; the
                    # last-chunk B's dep is its own stash (free).
                    if dep[1] % n != s:
                        ready += hop_cost_s
                cost = float(op_cost_s(kind, c, mb))
                start = max(free[s], ready)
                done[op] = start + cost
                free[s] = start + cost
                busy[s] += cost
                cursor[s] += 1
                completed += 1
                progressed = True
        if not progressed:
            raise RuntimeError("simulate_schedule: schedule deadlocks")
    wall = max(free) if n else 0.0
    return {
        "wall_s": wall,
        "busy_s": busy,
        "idle_s": [wall - b for b in busy],
        "efficiency": (
            sum(busy) / (n * wall) if wall > 0 else 0.0
        ),
    }


def partition_layers(
    n_layers: int,
    n_chunks: int,
    layer_ms: Optional[Sequence[float]] = None,
    *,
    embed_ms: float = 0.0,
    head_ms: float = 0.0,
) -> List[Tuple[int, int]]:
    """Contiguous [start, end) layer ranges per chunk minimizing the
    bottleneck chunk cost. `layer_ms` is per-layer cost (uniform when
    omitted — e.g. bench.py's measured `layer_ms` applies to every
    layer of a homogeneous stack); `embed_ms` loads chunk 0 and
    `head_ms` the last chunk — the asymmetric ends the
    `fixed_ms_breakdown` numbers name (embed + lm_head/loss), so a
    balanced partition gives the end chunks FEWER layers instead of
    pretending the stack is symmetric.

    DP over split points (O(L^2 * C)): exact bottleneck minimum, and
    L, C are tiny (<=128 layers, <=32 chunks)."""
    L, C = int(n_layers), int(n_chunks)
    if C < 1 or L < 0:
        raise ValueError(f"bad partition request ({L} layers, {C} chunks)")
    if C > L and L > 0:
        raise ValueError(f"more chunks ({C}) than layers ({L})")
    costs = (
        [float(c) for c in layer_ms]
        if layer_ms is not None
        else [1.0] * L
    )
    if len(costs) != L:
        raise ValueError(f"layer_ms has {len(costs)} entries for {L} layers")
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    def span(i: int, j: int, chunk: int) -> float:
        cost = prefix[j] - prefix[i]
        if chunk == 0:
            cost += float(embed_ms)
        if chunk == C - 1:
            cost += float(head_ms)
        return cost

    # best[c][j]: minimal bottleneck for layers [0, j) in chunks
    # [0..c]; parent pointers rebuild the split.
    INF = float("inf")
    best = [[INF] * (L + 1) for _ in range(C)]
    parent = [[0] * (L + 1) for _ in range(C)]
    for j in range(L + 1):
        best[0][j] = span(0, j, 0)
    for c in range(1, C):
        for j in range(L + 1):
            for i in range(j + 1):
                cand = max(best[c - 1][i], span(i, j, c))
                if cand < best[c][j]:
                    best[c][j] = cand
                    parent[c][j] = i
    bounds: List[Tuple[int, int]] = []
    j = L
    for c in range(C - 1, 0, -1):
        i = parent[c][j]
        bounds.append((i, j))
        j = i
    bounds.append((0, j))
    bounds.reverse()
    return bounds
