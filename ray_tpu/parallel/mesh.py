"""Device-mesh construction for TPU pods.

The TPU-native replacement for the reference's process-group world
(reference: train/torch/config.py:115 builds a NCCL process group; here
parallelism is expressed as a named `jax.sharding.Mesh` over which XLA
compiles ICI/DCN collectives — SURVEY.md §5.8).

Canonical axis names (outer→inner, matching link locality — inner axes
get the fastest links):

    dcn_dp — data parallel ACROSS SLICES over DCN (outermost: slowest
             links carry only the once-per-step gradient all-reduce)
    pp  — pipeline-parallel stage
    dp  — pure data parallel (replicated params)
    fsdp— data parallel with sharded params/optimizer (ZeRO-3 analog)
    sp  — sequence/context parallel (ring attention riders)
    tp  — tensor parallel (megatron-style, innermost, highest traffic)
    ep  — expert parallel for MoE (aliases onto sp/tp block as needed)

Multi-slice: a `dcn_dp > 1` spec builds a HYBRID mesh (the
`jax.experimental.mesh_utils.create_hybrid_device_mesh` layout): the
outer axis strides across slices (grouped by `device.slice_index` on
real multi-slice TPU; contiguous blocks on virtual test meshes) so
every inner axis stays inside one slice's ICI domain. This replaces
the reference's multi-node NCCL world (reference:
train/torch/config.py:115) for cross-slice scale — SURVEY.md §5.8.
"""

from __future__ import annotations

import math
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dcn_dp", "pp", "dp", "fsdp", "sp", "tp")


def group_by_slice(devices: Sequence) -> List[list]:
    """Partition devices into slices. Real multi-slice TPU devices
    carry `slice_index`; single-slice and virtual CPU devices don't
    (treated as one slice — callers split explicitly for tests)."""
    groups: Dict[int, list] = defaultdict(list)
    for d in devices:
        groups[getattr(d, "slice_index", 0) or 0].append(d)
    return [groups[k] for k in sorted(groups)]


@dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout; `build()` realizes it on devices."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1  # folded into (sp, tp) when building; see build()
    dcn_dp: int = 1  # data-parallel replicas across slices (DCN)

    def num_devices(self) -> int:
        return (
            self.dcn_dp * self.dp * self.fsdp * self.tp * self.sp * self.pp
        )

    @staticmethod
    def auto(
        n_devices: Optional[int] = None,
        *,
        tp: int = 1,
        sp: int = 1,
        pp: int = 1,
        dcn_dp: int = 1,
    ) -> "MeshSpec":
        """Fill the fsdp axis with whatever devices remain."""
        n = n_devices if n_devices is not None else len(jax.devices())
        denom = tp * sp * pp * dcn_dp
        if n % denom != 0:
            raise ValueError(
                f"{n} devices not divisible by tp*sp*pp*dcn_dp={denom}"
            )
        return MeshSpec(
            fsdp=n // denom, tp=tp, sp=sp, pp=pp, dcn_dp=dcn_dp
        )

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        need = self.num_devices()
        if len(devices) < need:
            raise ValueError(
                f"MeshSpec needs {need} devices, have {len(devices)}"
            )
        if self.dcn_dp > 1:
            return self._build_hybrid(devices)
        shape = (1, self.pp, self.dp, self.fsdp, self.sp, self.tp)
        grid = np.array(devices[:need]).reshape(shape)
        return Mesh(grid, AXES)

    def _build_hybrid(self, devices: Sequence) -> Mesh:
        """Hybrid ICI x DCN layout: outer dcn_dp axis = one slice per
        entry, inner axes laid out within each slice (semantics of
        mesh_utils.create_hybrid_device_mesh)."""
        slices = group_by_slice(devices)
        per_slice = self.num_devices() // self.dcn_dp
        if len(slices) == 1:
            # No slice topology reported (virtual CPU mesh, or a
            # runtime that doesn't expose slice_index): split into
            # contiguous blocks in (process, device) order, so a
            # multi-process gang with rank-contiguous slices (what
            # JaxBackend sets up) keeps each block inside one
            # process group — high-traffic inner axes never straddle
            # the process boundary that models DCN.
            flat = sorted(
                slices[0],
                key=lambda d: (
                    getattr(d, "process_index", 0) or 0,
                    getattr(d, "id", 0),
                ),
            )
            slices = [
                flat[i * per_slice : (i + 1) * per_slice]
                for i in range(self.dcn_dp)
            ]
        if len(slices) < self.dcn_dp:
            raise ValueError(
                f"dcn_dp={self.dcn_dp} but only {len(slices)} slices"
            )
        for group in slices[: self.dcn_dp]:
            if len(group) < per_slice:
                raise ValueError(
                    f"slice contributes {len(group)} devices, "
                    f"need {per_slice} per slice"
                )
        inner = (1, self.pp, self.dp, self.fsdp, self.sp, self.tp)
        grid = np.stack(
            [
                np.array(group[:per_slice]).reshape(inner)[0]
                for group in slices[: self.dcn_dp]
            ]
        )
        return Mesh(grid, AXES)

    def axis_sizes(self) -> Dict[str, int]:
        return {
            "dcn_dp": self.dcn_dp,
            "pp": self.pp,
            "dp": self.dp,
            "fsdp": self.fsdp,
            "sp": self.sp,
            "tp": self.tp,
        }


def single_host_mesh(**axis_sizes) -> Mesh:
    return MeshSpec(**axis_sizes).build()


def data_axes() -> Tuple[str, ...]:
    """Mesh axes a batch dimension is sharded over."""
    return ("dcn_dp", "dp", "fsdp")


def model_axes() -> Tuple[str, ...]:
    return ("tp",)


def batch_size_per_host(global_batch: int, mesh: Mesh) -> int:
    n_data = math.prod(mesh.shape[a] for a in data_axes())
    if global_batch % n_data != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by data-parallel "
            f"size {n_data}"
        )
    return global_batch // n_data
