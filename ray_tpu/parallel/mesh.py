"""Device-mesh construction for TPU pods.

The TPU-native replacement for the reference's process-group world
(reference: train/torch/config.py:115 builds a NCCL process group; here
parallelism is expressed as a named `jax.sharding.Mesh` over which XLA
compiles ICI/DCN collectives — SURVEY.md §5.8).

Canonical axis names (outer→inner, matching ICI locality — inner axes
get the fastest links):

    pp  — pipeline-parallel stage
    dp  — pure data parallel (replicated params)
    fsdp— data parallel with sharded params/optimizer (ZeRO-3 analog)
    sp  — sequence/context parallel (ring attention riders)
    tp  — tensor parallel (megatron-style, innermost, highest traffic)
    ep  — expert parallel for MoE (aliases onto sp/tp block as needed)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("pp", "dp", "fsdp", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout; `build()` realizes it on devices."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1  # folded into (sp, tp) when building; see build()

    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.pp

    @staticmethod
    def auto(
        n_devices: Optional[int] = None,
        *,
        tp: int = 1,
        sp: int = 1,
        pp: int = 1,
    ) -> "MeshSpec":
        """Fill the fsdp axis with whatever devices remain."""
        n = n_devices if n_devices is not None else len(jax.devices())
        denom = tp * sp * pp
        if n % denom != 0:
            raise ValueError(
                f"{n} devices not divisible by tp*sp*pp={denom}"
            )
        return MeshSpec(fsdp=n // denom, tp=tp, sp=sp, pp=pp)

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        need = self.num_devices()
        if len(devices) < need:
            raise ValueError(
                f"MeshSpec needs {need} devices, have {len(devices)}"
            )
        shape = (self.pp, self.dp, self.fsdp, self.sp, self.tp)
        grid = np.array(devices[:need]).reshape(shape)
        return Mesh(grid, AXES)

    def axis_sizes(self) -> Dict[str, int]:
        return {
            "pp": self.pp,
            "dp": self.dp,
            "fsdp": self.fsdp,
            "sp": self.sp,
            "tp": self.tp,
        }


def single_host_mesh(**axis_sizes) -> Mesh:
    return MeshSpec(**axis_sizes).build()


def data_axes() -> Tuple[str, ...]:
    """Mesh axes a batch dimension is sharded over."""
    return ("dp", "fsdp")


def model_axes() -> Tuple[str, ...]:
    return ("tp",)


def batch_size_per_host(global_batch: int, mesh: Mesh) -> int:
    n_data = math.prod(mesh.shape[a] for a in data_axes())
    if global_batch % n_data != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by data-parallel "
            f"size {n_data}"
        )
    return global_batch // n_data
