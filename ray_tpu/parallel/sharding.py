"""Logical-axis sharding rules → GSPMD PartitionSpecs.

This is where the framework's parallelisms (SURVEY.md §2.4) become XLA
shardings: parameters/activations carry *logical* axis names and a rule
table maps them onto mesh axes. XLA's GSPMD partitioner then inserts
the collectives the reference would have issued through NCCL.

Default rule table (transformer nomenclature):

    batch   → (dp, fsdp)     activations data-parallel
    seq     → sp             sequence/context parallelism
    embed   → fsdp (params)  ZeRO-3-style parameter sharding
    heads   → tp             attention-head tensor parallelism
    mlp     → tp             feed-forward tensor parallelism
    vocab   → tp             embedding/logit sharding
    expert  → ep→(sp,tp)     MoE expert parallelism
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Dict[str, Union[str, Tuple[str, ...], None]]

#: Parameter rules — fsdp shards the embed dim of weights (ZeRO-3).
#: Params are REPLICATED across dcn_dp (pure DP between slices: the
#: only cross-slice traffic GSPMD then inserts is the per-step
#: gradient all-reduce, which is what DCN can afford).
PARAM_RULES: Rules = {
    "batch": ("dcn_dp", "dp", "fsdp"),
    "seq": None,
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "vocab": "tp",
    "expert": None,
    "layers": None,
    "head_dim": None,
}

#: Activation rules — batch over data axes, seq over sp, heads over tp.
ACT_RULES: Rules = {
    "batch": ("dcn_dp", "dp", "fsdp"),
    "seq": "sp",
    "embed": None,
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "vocab": "tp",
    "expert": None,
    "head_dim": None,
}


def spec_for(logical_axes: Sequence[Optional[str]], rules: Rules) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    parts = []
    for name in logical_axes:
        if name is None:
            parts.append(None)
        else:
            parts.append(rules.get(name))
    return P(*parts)


def named_sharding(
    mesh: Mesh, logical_axes: Sequence[Optional[str]], rules: Rules
) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules))


@dataclass(frozen=True)
class Annotated:
    """A leaf annotation: array shape dims ↔ logical axis names."""

    logical_axes: Tuple[Optional[str], ...]


def annotate(*logical_axes: Optional[str]) -> Annotated:
    return Annotated(tuple(logical_axes))


def tree_shardings(
    mesh: Mesh, annotations: Any, rules: Rules
) -> Any:
    """Map a pytree of `Annotated` (or None) to NamedShardings."""

    def leaf(a):
        if isinstance(a, Annotated):
            return named_sharding(mesh, a.logical_axes, rules)
        return NamedSharding(mesh, P())

    return jax.tree.map(
        leaf, annotations, is_leaf=lambda x: isinstance(x, Annotated) or x is None
    )


def shard_tree(mesh: Mesh, tree: Any, annotations: Any, rules: Rules) -> Any:
    """Device-put a pytree according to its annotations."""
    shardings = tree_shardings(mesh, annotations, rules)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s), tree, shardings
    )


def with_constraint(x, mesh: Mesh, logical_axes, rules: Rules):
    """In-jit sharding constraint by logical names."""
    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, logical_axes, rules)
    )


def checked_shard_map(f, mesh: Mesh, in_specs, out_specs):
    """shard_map with a version-adaptive replication check: jax >= 0.6
    proves psum-derived scalars replicated and keeps the check ON; the
    0.4-era checker cannot follow the pipeline's ppermute/psum chains
    and would reject correct programs (out_specs=P() _SpecError), so
    it is disabled there. Gate: `lax.pcast` existing is the same
    varying-manual-axes generation whose checker works."""
    try:
        from jax import shard_map as _shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if not hasattr(jax.lax, "pcast"):
        kwargs["check_rep"] = False
    return _shard_map(f, **kwargs)
