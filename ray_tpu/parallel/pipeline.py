"""Pipeline parallelism over the `pp` mesh axis.

The reference's building blocks for pipelining are compiled actor
DAGs over NCCL channels (reference: dag/compiled_dag_node.py:691,
experimental/channel/torch_tensor_nccl_channel.py) — i.e. stage hops
travel through the runtime. The TPU-native design keeps the whole
pipeline INSIDE one jitted SPMD program: every pp rank holds its
stage's parameters, microbatch activations hop stages via
`lax.ppermute` over ICI, and the classic GPipe skew schedule
(num_microbatches + num_stages - 1 ticks) keeps all stages busy.
XLA overlaps the neighbor hop with stage compute; no runtime channel
is involved. The cross-host version of the same schedule rides the
compiled actor DAG (ray_tpu.dag) with one SPMD program per stage gang.

Use inside shard_map: the wrapper `spmd_pipeline` masks the pipeline
bubble, injects microbatch i into stage 0 at tick i, and emits stage
N-1's output at tick i+N-1. Differentiable end to end (ppermute has a
transpose rule), so pipeline-parallel training composes with jax.grad.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .collective import axis_size as _axis_size, pcast_varying


def spmd_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    *,
    axis_name: str = "pp",
    stacked_params: bool = True,
    with_aux: bool = False,
):
    """Run a stage-partitioned function over microbatches.

    stage_fn(stage_params, x) — this rank's stage; all ranks call it
    every tick (SPMD), invalid ticks are masked. With `with_aux=True`
    it must return (y, aux_scalar); aux from valid ticks is summed
    rank-locally across ticks (aux never travels between stages — sum
    it over `axis_name` with a psum to get the pipeline total).
    stage_params — a stacked [n_stages, ...] param tree sharded
    P('pp', ...); shard_map hands each rank its [1, ...] slice and the
    singleton stage axis is stripped here (pass stacked_params=False
    if the tree is already per-rank).
    microbatches — [num_mb, mb, ...] input, same on every rank (only
    stage 0 actually consumes it).

    Returns [num_mb, mb, ...] outputs (or (outputs, aux_sum) with
    with_aux), valid on the LAST stage's ranks (other ranks hold
    zeros); use `broadcast_from_last_stage` if every rank needs them.
    """
    if stacked_params:
        stage_params = jax.tree.map(lambda a: a[0], stage_params)
    n = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    num_mb = microbatches.shape[0]
    ticks = num_mb + n - 1
    # Stage hop: rank i's output becomes rank i+1's input.
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(t, carry):
        state, outputs, aux_acc = carry
        # state: activation entering this rank's stage this tick.
        mb_index = t - rank  # microbatch this stage works on
        inject = jnp.take(
            microbatches,
            jnp.clip(t, 0, num_mb - 1),
            axis=0,
        )
        x = jnp.where(rank == 0, inject, state)
        if with_aux:
            y, aux = stage_fn(stage_params, x)
        else:
            y, aux = stage_fn(stage_params, x), 0.0
        valid = (mb_index >= 0) & (mb_index < num_mb)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        # Last stage banks its finished microbatch.
        out_index = jnp.clip(t - (n - 1), 0, num_mb - 1)
        write = valid & (rank == n - 1)
        outputs = jnp.where(
            write,
            outputs.at[out_index].set(y),
            outputs,
        )
        state = lax.ppermute(y, axis_name, perm)
        return state, outputs, aux_acc

    # The carry is device-varying over pp (each rank holds different
    # activations); mark the zero initializers so scan's type check
    # agrees (jax >= 0.7 varying-manual-axes; a no-op on older jax
    # without pcast). zeros_like inherits any OTHER varying axes
    # (sp/ep) the activations already carry when the pipeline composes
    # with sequence/expert parallelism.
    state = pcast_varying(
        jnp.zeros_like(jnp.take(microbatches, 0, axis=0)),
        axis_name,
    )
    outputs = pcast_varying(jnp.zeros_like(microbatches), axis_name)
    aux_acc = pcast_varying(jnp.zeros((), jnp.float32), axis_name)
    _, outputs, aux_acc = lax.fori_loop(
        0, ticks, tick, (state, outputs, aux_acc)
    )
    return (outputs, aux_acc) if with_aux else outputs


def broadcast_from_last_stage(
    outputs: jax.Array, axis_name: str = "pp"
) -> jax.Array:
    """All ranks get the last stage's outputs (zeros elsewhere make a
    psum a broadcast)."""
    return lax.psum(outputs, axis_name)


def stack_stage_params(per_stage_params: list) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with a leading
    stage axis, ready to shard over pp (P('pp', ...))."""
    return jax.tree.map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )
