"""Parallelism layer: named meshes, logical sharding rules, and an
explicit collective API that compiles to XLA/ICI collectives."""

from . import collective, schedule
from .mesh import (
    AXES,
    MeshSpec,
    batch_size_per_host,
    data_axes,
    model_axes,
    single_host_mesh,
)
from .sharding import (
    ACT_RULES,
    PARAM_RULES,
    Annotated,
    annotate,
    named_sharding,
    shard_tree,
    spec_for,
    tree_shardings,
    with_constraint,
)

__all__ = [
    "AXES",
    "MeshSpec",
    "single_host_mesh",
    "batch_size_per_host",
    "data_axes",
    "model_axes",
    "collective",
    "schedule",
    "ACT_RULES",
    "PARAM_RULES",
    "Annotated",
    "annotate",
    "named_sharding",
    "shard_tree",
    "spec_for",
    "tree_shardings",
    "with_constraint",
]
