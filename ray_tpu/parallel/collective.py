"""Explicit collective API compiling to XLA collectives.

API modeled on the reference's `ray.util.collective` (reference:
python/ray/util/collective/collective.py:258-615 — allreduce,
allgather, reducescatter, broadcast, send/recv, barrier over NCCL/GLOO
groups). TPU-native difference (SURVEY.md §5.8): these are *traced*
primitives used inside `shard_map`-decorated functions over a named
mesh axis, so XLA schedules them on ICI — there is no runtime
communicator object to manage and no NCCL.

Example:

    mesh = MeshSpec(fsdp=8).build()
    @partial(shard_map, mesh=mesh, in_specs=P("fsdp"), out_specs=P("fsdp"))
    def step(x):
        g = allreduce(local_grad(x), "fsdp")
        ...
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

Axis = Union[str, Sequence[str]]


def allreduce(x, axis: Axis, op: str = "sum"):
    """Reduce across the mesh axis; all members get the result
    (reference: collective.py:258 allreduce)."""
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "prod":
        # Gather-then-multiply handles zeros and negatives exactly
        # (a log/exp trick would NaN on them).
        gathered = lax.all_gather(x, axis)
        return jnp.prod(gathered, axis=0)
    raise ValueError(f"unsupported reduce op: {op}")


def allgather(x, axis: Axis, *, concat_axis: int = 0, tiled: bool = True):
    """Gather shards from every member of the axis
    (reference: collective.py:371 allgather)."""
    return lax.all_gather(x, axis, axis=concat_axis, tiled=tiled)


def reducescatter(x, axis: Axis, *, scatter_axis: int = 0, op: str = "sum"):
    """Reduce then scatter shards (reference: collective.py:443)."""
    if op not in ("sum", "mean"):
        raise ValueError(f"unsupported reducescatter op: {op}")
    out = lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)
    if op == "mean":
        out = out / axis_size(axis)
    return out


def broadcast(x, axis: Axis, root: int = 0):
    """Every member receives root's value (reference: collective.py:300).

    Non-root values are discarded with `where` (not multiplied by 0,
    which would propagate their NaN/Inf) before a psum that XLA lowers
    to an ICI broadcast.
    """
    idx = lax.axis_index(axis)
    selected = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(selected, axis)


def send_recv(x, axis: Axis, *, shift: int = 1):
    """Neighbor exchange on a ring: each member sends its value
    `shift` steps forward and receives from `shift` steps back
    (reference p2p: collective.py:531 send / :594 recv; here a single
    fused ppermute, which is how rings ride ICI)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def barrier(axis: Axis):
    """Synchronize members of the axis (reference: collective.py:615).

    Under XLA a barrier is a collective with trivial payload.
    """
    return lax.psum(jnp.zeros((), dtype=jnp.float32), axis)


def all_to_all(
    x,
    axis: Axis,
    *,
    split_axis: int,
    concat_axis: int,
):
    """All-to-all reshard — the Ulysses sequence-parallelism primitive
    (SURVEY.md §5.7): swap which array dimension is sharded over the
    mesh axis."""
    return lax.all_to_all(x, axis, split_axis, concat_axis, tiled=True)


def axis_index(axis: Axis):
    return lax.axis_index(axis)


def axis_size(axis: Axis):
    """Static size of a named mesh axis, on any jax this repo meets:
    `lax.axis_size` where it exists (>= 0.6), else `psum(1, axis)` —
    which constant-folds to the same Python int at trace time."""
    try:
        return lax.axis_size(axis)
    except AttributeError:
        return lax.psum(1, axis)


def pcast_varying(x, axes):
    """Mark `x` varying over `axes` for jax >= 0.7's
    varying-manual-axes type check; a no-op per axis when the axis is
    already varying or the jax predates `lax.pcast` (same guard idiom
    as ops/ring_attention._varying)."""
    if isinstance(axes, str):
        axes = (axes,)
    for ax in axes:
        try:
            x = lax.pcast(x, (ax,), to="varying")
        except (AttributeError, TypeError, ValueError):
            pass
    return x
