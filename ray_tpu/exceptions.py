"""User-facing exceptions (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at `get` with the remote
    traceback attached (reference: RayTaskError in
    python/ray/exceptions.py)."""

    def __init__(self, cause_repr: str, traceback_str: str = ""):
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        super().__init__(
            f"Task failed with {cause_repr}\n"
            f"--- remote traceback ---\n{traceback_str}"
        )


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor owning the called method is dead."""


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """An object was evicted/lost and could not be reconstructed."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get` exceeded its timeout."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before/while running."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing the runtime environment for a task/actor failed."""


class ObjectStoreFullError(RayTpuError):
    """The shared-memory store could not fit the object."""


class PlacementGroupUnavailableError(RayTpuError):
    """Placement-group bundles could not be reserved."""
