"""Cluster CLI.

Reference: python/ray/scripts/scripts.py — `ray start --head` /
`ray start --address=...` bring nodes up (:644), `ray stop`, `ray
status`, `ray job submit`, and the `ray list ...` state commands
(util/state/state_cli.py). Invoked as `python -m ray_tpu <cmd>`.

The head daemon runs in the foreground of the `start` process (use
`&`, systemd, or a supervisor to daemonize); its address + pid land in
a cluster-info file (default /tmp/rt_cluster_info.json) that the other
commands read.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

DEFAULT_INFO_PATH = "/tmp/rt_cluster_info.json"


def _run_until_signal(cleanup) -> None:
    """Foreground service loop: park until SIGTERM/SIGINT, then run
    `cleanup` (shared by start/up/dashboard)."""
    stop = {"flag": False}

    def on_term(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        cleanup()


def _pid_exited(pid: int) -> bool:
    """True once the process is gone OR a zombie (exited, unreaped by
    its parent) — os.kill(pid, 0) alone treats zombies as alive."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[0] == "Z"
    except (OSError, IndexError):
        return True


def _resolve_address(args) -> str:
    if getattr(args, "address", None):
        return args.address
    env = os.environ.get("RT_ADDRESS")
    if env:
        return env
    try:
        with open(args.cluster_info) as f:
            info = json.load(f)
        # Same-box convenience: the head recorded its TCP auth token.
        if info.get("auth_token") and not os.environ.get("RT_AUTH_TOKEN"):
            os.environ["RT_AUTH_TOKEN"] = info["auth_token"]
        return info["address"]
    except (OSError, KeyError, json.JSONDecodeError):
        sys.exit(
            "no cluster found: pass --address, set RT_ADDRESS, or "
            f"start one with `python -m ray_tpu start --head` "
            f"(looked in {args.cluster_info})"
        )


def cmd_start(args) -> None:
    import tempfile

    from .._private.accelerators import detect_accelerators
    from .._private.config import Config
    from .._private.daemon import NodeDaemon

    # TCP listeners authenticate every frame with HMAC keyed by the
    # cluster token (rpc.py). Generate one for new TCP heads so the
    # wire never runs on the well-known local key; joining nodes take
    # it from --auth-token / RT_AUTH_TOKEN / the cluster-info file.
    if getattr(args, "auth_token", None):
        os.environ["RT_AUTH_TOKEN"] = args.auth_token
    elif args.listen_host and args.head and not os.environ.get(
        "RT_AUTH_TOKEN"
    ):
        import secrets

        os.environ["RT_AUTH_TOKEN"] = secrets.token_hex(16)
        print(
            "generated cluster auth token (joining nodes need it): "
            f"RT_AUTH_TOKEN={os.environ['RT_AUTH_TOKEN']}"
        )

    config = Config.from_env(None)
    resources = json.loads(args.resources) if args.resources else {}
    resources.setdefault(
        "CPU",
        float(args.num_cpus if args.num_cpus is not None else os.cpu_count()),
    )
    detected, labels = detect_accelerators(
        {"TPU": float(args.num_tpus)} if args.num_tpus is not None else None
    )
    if getattr(args, "labels", None):
        labels.update(json.loads(args.labels))
    for name, amount in detected.items():
        resources.setdefault(name, amount)
    resources.setdefault("memory", float(2**34))
    session_dir = args.session_dir or tempfile.mkdtemp(prefix="rt_node_")
    if args.head:
        daemon = NodeDaemon(
            session_dir,
            resources,
            config,
            is_head=True,
            labels=labels,
            listen_host=args.listen_host,
            listen_port=args.listen_port,
        )
        daemon.start()
        info = {
            "address": daemon.address,
            "pid": os.getpid(),
            "session_dir": session_dir,
        }
        if args.listen_host and os.environ.get("RT_AUTH_TOKEN"):
            info["auth_token"] = os.environ["RT_AUTH_TOKEN"]
        with open(args.cluster_info, "w") as f:
            json.dump(info, f)
        print(f"head started: address={daemon.address}")
        print(
            "connect with ray_tpu.init(address="
            f"{daemon.address!r}) or RT_ADDRESS={daemon.address}"
        )
    else:
        head_address = _resolve_address(args)
        daemon = NodeDaemon(
            session_dir,
            resources,
            config,
            is_head=False,
            head_address=head_address,
            labels=labels,
            listen_host=args.listen_host,
            listen_port=args.listen_port,
        )
        daemon.start()
        print(f"node started, joined head at {head_address}")

    def cleanup():
        daemon.shutdown()
        if args.head:
            try:
                os.remove(args.cluster_info)
            except OSError:
                pass

    _run_until_signal(cleanup)


def cmd_stop(args) -> None:
    try:
        with open(args.cluster_info) as f:
            info = json.load(f)
    except OSError:
        print("no running cluster found")
        return
    try:
        os.kill(info["pid"], signal.SIGTERM)
        print(f"sent SIGTERM to head (pid {info['pid']})")
    except ProcessLookupError:
        print("head process already gone")
        try:
            os.remove(args.cluster_info)
        except OSError:
            pass


def _connect(args):
    import ray_tpu as rt

    rt.init(address=_resolve_address(args))
    return rt


def cmd_status(args) -> None:
    rt = _connect(args)
    nodes = rt.nodes()
    print(f"nodes: {len(nodes)}")
    for node in nodes:
        mark = " (head)" if node.get("is_head") else ""
        print(
            f"  {node['node_id'][:12]}{mark} alive={node['alive']} "
            f"resources={node['resources']}"
        )
    print("cluster totals:", rt.cluster_resources())
    print("available:    ", rt.available_resources())


def cmd_summary(args) -> None:
    rt = _connect(args)
    print(json.dumps(rt.state_summary(), indent=2, default=str))


def cmd_list(args) -> None:
    _connect(args)
    from ..util import state

    kind = args.kind
    rows = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "tasks": state.list_tasks,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
    }[kind]()
    print(json.dumps(rows, indent=2, default=str))


def cmd_submit(args) -> None:
    from ..job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    runtime_env = {}
    if args.working_dir:
        runtime_env["working_dir"] = args.working_dir
    import shlex

    entrypoint = list(args.entrypoint)
    if entrypoint and entrypoint[0] == "--":
        entrypoint = entrypoint[1:]
    if not entrypoint:
        sys.exit("submit: missing entrypoint command")
    job_id = client.submit_job(
        entrypoint=" ".join(shlex.quote(t) for t in entrypoint),
        runtime_env=runtime_env or None,
    )
    print(f"submitted {job_id}")
    if args.no_wait:
        return
    status = client.wait_until_finished(job_id, timeout=args.timeout)
    print(f"status: {status.value}")
    logs = client.get_job_logs(job_id)
    if logs:
        print("--- logs ---")
        print(logs, end="")
    if status != JobStatus.SUCCEEDED:
        sys.exit(1)


def cmd_jobs(args) -> None:
    from ..job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    print(json.dumps(client.list_jobs(), indent=2, default=str))


def cmd_logs(args) -> None:
    from ..job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    try:
        client.get_job_status(args.job_id)
    except Exception:
        # Unknown ids otherwise print nothing with exit 0 — a typo'd
        # id in a scripted log fetch must fail loudly.
        sys.exit(f"no such job: {args.job_id}")
    print(client.get_job_logs(args.job_id), end="")


def _memory_problems(verdict: dict) -> list:
    """Flatten `verdict.memory` into the problem rows the exit-code
    contract counts (shared by `memory` and the doctor's summary)."""
    return (
        list(verdict.get("near_capacity") or ())
        + list(verdict.get("leak_suspects") or ())
        + list(verdict.get("spill_thrash") or ())
    )


def cmd_memory(args) -> None:
    """`ray_tpu memory` — the cluster memory ledger (reference: `ray
    memory`, util/state/memory_utils.py, grown per-job): top consumers
    by job/node/owner, per-job bytes·s and chip·s, leak suspects and
    near-capacity nodes. Exit-code contract matches lint/check/doctor:
    0 healthy, 1 when `verdict.memory` has findings."""
    _connect(args)
    from ..util.state import memory_summary

    if getattr(args, "transfers", False):
        _print_transfers(args)
        return
    mem = memory_summary()
    verdict = mem.get("verdict") or {}
    problems = _memory_problems(verdict)
    if args.as_json:
        print(json.dumps(mem, indent=2, default=str))
        sys.exit(1 if problems else 0)
    if mem.get("disabled"):
        print(
            "memory ledger disabled (memory_report_interval_s=0) — "
            "no attribution, series, or verdict.memory"
        )
        return
    totals = mem.get("totals") or {}
    used = totals.get("arena_used", 0)
    capacity = totals.get("arena_capacity", 0)
    jobs = mem.get("jobs") or {}
    nodes = mem.get("nodes") or []
    n_objects = sum(n.get("tracked_objects", 0) for n in nodes)
    print(
        f"{n_objects} objects, {used / 1e6:.1f} / "
        f"{capacity / 1e6:.0f} MB arena in use across "
        f"{len(nodes)} node(s), "
        f"{totals.get('spilled_bytes', 0) / 1e6:.1f} MB spilled"
    )
    print(
        f"attributed to (job, owner) pairs: "
        f"{totals.get('attributed_bytes', 0) / 1e6:.1f} MB "
        f"({100.0 * totals.get('attribution_fraction', 0.0):.1f}% "
        "of arena-used bytes)"
    )
    for job, row in sorted(
        jobs.items(),
        key=lambda kv: kv[1].get("object_bytes", 0),
        reverse=True,
    ):
        extras = []
        if "object_byte_seconds" in row:
            extras.append(
                f"{row['object_byte_seconds'] / 1e9:.2f} GB·s"
            )
        if "chip_seconds" in row:
            extras.append(f"{row['chip_seconds']:.1f} chip·s")
        suffix = f" ({', '.join(extras)})" if extras else ""
        print(
            f"  job {job}: {row.get('object_bytes', 0) / 1e6:.1f} MB "
            f"in {row.get('objects', 0)} objects, "
            f"{row.get('pinned_objects', 0)} pinned{suffix}"
        )
    for node in nodes:
        print(
            f"  node {node.get('node', '?')[:12]}: "
            f"{node.get('arena_used', 0) / 1e6:.1f} / "
            f"{node.get('arena_capacity', 0) / 1e6:.0f} MB, "
            f"{node.get('tracked_objects', 0)} objects, "
            f"{node.get('spilled_objects', 0)} spilled"
        )
    if args.verbose:
        print("top owners:")
        for row in mem.get("owners", []):
            print(
                f"  job {row['job']} {row['owner']}: "
                f"{row['bytes'] / 1e6:.1f} MB in "
                f"{row['objects']} objects"
            )
        print("top objects:")
        for row in mem.get("top_objects", []):
            print(
                f"  {row['object_id'][:16]} {row['size'] / 1e6:.1f} MB "
                f"job={row.get('job', '')} owner={row.get('owner', '')} "
                f"age={row.get('age_s', 0):.0f}s"
                f"{' pinned' if row.get('pinned') else ''}"
                f"{' spilled' if row.get('spilled') else ''}"
            )
    if not problems:
        print("memory verdict: HEALTHY")
        return
    print(f"memory verdict: {len(problems)} finding(s)")
    for problem in problems:
        print(f"  {problem.get('detail')}")
    sys.exit(1)


def _print_transfers(args) -> None:
    """`ray_tpu memory --transfers` — the cluster transfer matrix:
    who moved which job's bytes where, how long the moves took, and
    how each job's gets resolved (provenance + locality)."""
    from ..util.state import transfer_summary

    transfers = transfer_summary()
    if args.as_json:
        print(json.dumps(transfers, indent=2, default=str))
        return
    if transfers.get("disabled"):
        print(
            "transfer matrix disabled (memory_report_interval_s=0 "
            "or transfer_report_interval_s=0)"
        )
        return
    flows = transfers.get("flows") or []
    if not flows:
        print("no transfers recorded")
    else:
        print(f"{len(flows)} flow(s), bytes descending:")
        for flow in flows:
            arrow = (
                f"{(flow.get('src') or '?')[:12]} -> "
                f"{(flow.get('dst') or '?')[:12]}"
            )
            print(
                f"  job {(flow.get('job') or '-')[:8]} {arrow}: "
                f"{flow.get('bytes', 0) / 1e6:.1f} MB in "
                f"{flow.get('pulls', 0)} pull(s), "
                f"{flow.get('restores', 0)} restore(s), "
                f"{flow.get('aborted', 0)} aborted, "
                f"{flow.get('ms', 0.0):.1f} ms "
                f"({flow.get('mb_per_s', 0.0):.1f} MB/s)"
            )
    locality = transfers.get("locality") or {}
    for job, row in locality.items():
        print(
            f"  job {job[:8]} locality: {row.get('hits', 0)} hit(s) / "
            f"{row.get('misses', 0)} miss(es) "
            f"({100.0 * row.get('hit_fraction', 0.0):.1f}% local)"
        )
    if args.verbose:
        print("get provenance by job:")
        for job, provs in (transfers.get("provenance") or {}).items():
            for prov, row in provs.items():
                print(
                    f"  job {job[:8]} {prov}: {row.get('gets', 0)} "
                    f"get(s), {row.get('bytes', 0) / 1e6:.1f} MB, "
                    f"{row.get('wait_ms', 0.0):.1f} ms waited"
                )
        print("top remote-pulling task classes:")
        for row in transfers.get("tasks") or []:
            print(
                f"  {row.get('task') or 'driver'} "
                f"(job {(row.get('job') or '-')[:8]}): "
                f"{row.get('remote_bytes', 0) / 1e6:.1f} MB remote / "
                f"{row.get('local_bytes', 0) / 1e6:.1f} MB local"
            )


def cmd_timeline(args) -> None:
    """Chrome-trace export (reference: `ray timeline`)."""
    _connect(args)
    from ..util.tracing import export_timeline

    trace = export_timeline(args.out)
    print(f"wrote {len(trace)} trace events to {args.out}")


def _load_cluster_config(path: str) -> dict:
    with open(path) as f:
        if path.endswith((".yaml", ".yml")):
            import yaml

            return yaml.safe_load(f)
        return json.load(f)


def cmd_up(args) -> None:
    """Launch an autoscaling cluster from a config file (reference:
    `ray up cluster.yaml` — autoscaler/_private/commands.py). The
    head + autoscaler run in THIS process's foreground (use & or a
    supervisor to daemonize); `down` signals it via the cluster-info
    file. Provider `fake` boots in-box daemons; provider `gcp_tpu`
    drives the TPU REST surface — against the hermetic fake service
    here (production constructs GcpTpuNodeProvider with the real REST
    transport + credentials)."""
    from ..autoscaler.cluster import (
        AutoscalingCluster,
        TpuAutoscalingCluster,
    )

    config = _load_cluster_config(args.config)
    if not isinstance(config, dict):
        sys.exit(
            f"cluster config {args.config} must be a mapping "
            f"(got {type(config).__name__}: empty file?)"
        )
    provider = (config.get("provider") or {}).get("type", "fake")
    if provider == "gcp_tpu":
        cluster = TpuAutoscalingCluster(
            head_resources=config.get("head_resources"),
            tpu_node_types=config.get("tpu_node_types"),
            idle_timeout_s=float(config.get("idle_timeout_s", 3.0)),
        )
    elif provider == "fake":
        cluster = AutoscalingCluster(
            head_resources=config.get("head_resources"),
            worker_node_types=config.get("worker_node_types"),
            idle_timeout_s=float(config.get("idle_timeout_s", 3.0)),
        )
    else:
        sys.exit(
            f"unknown provider type {provider!r} (supported: fake, "
            "gcp_tpu)"
        )
    cluster.start()
    info = {
        "address": cluster.address,
        "pid": os.getpid(),
        "cluster_name": config.get("cluster_name", "rt-cluster"),
    }
    with open(args.cluster_info, "w") as f:
        json.dump(info, f)
    print(
        f"cluster up: address={cluster.address} "
        f"(info in {args.cluster_info}; `python -m ray_tpu down` "
        "to stop)",
        flush=True,
    )

    def cleanup():
        cluster.shutdown()
        try:
            os.unlink(args.cluster_info)
        except OSError:
            pass

    _run_until_signal(cleanup)


def cmd_down(args) -> None:
    """Stop a cluster started with `up` (reference: `ray down`)."""
    try:
        with open(args.cluster_info) as f:
            info = json.load(f)
    except (OSError, json.JSONDecodeError):
        sys.exit(f"no cluster-info file at {args.cluster_info}")
    pid = info.get("pid")
    if not pid:
        sys.exit("cluster-info file has no pid")
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        print("cluster process already gone; removing stale info file")
        try:
            os.unlink(args.cluster_info)
        except OSError:
            pass
        return
    # Wait for the `up` process to finish its graceful shutdown
    # (zombie-aware: a supervisor may reap lazily).
    for _ in range(100):
        if _pid_exited(pid):
            print("cluster stopped")
            return
        time.sleep(0.1)
    print(f"cluster pid {pid} still shutting down (SIGTERM sent)")


def cmd_serve_run(args) -> None:
    """`serve run module:app` (reference: serve/scripts.py:455 — import
    the bound Application, deploy it, serve until SIGINT/SIGTERM)."""
    import importlib

    rt = _connect(args)
    from .. import serve

    module_name, _, attr = args.import_path.partition(":")
    if not attr:
        sys.exit(
            "serve run takes module:attr (e.g. my_app:app, where "
            "`app = MyDeployment.bind(...)`)"
        )
    sys.path.insert(0, os.getcwd())
    try:
        app = getattr(importlib.import_module(module_name), attr)
    except (ImportError, AttributeError) as e:
        sys.exit(f"cannot import {args.import_path!r}: {e}")
    # start() returns the ACTUAL bound port: when proxies already
    # exist (a prior run), --port is a no-op and the live port wins.
    port = serve.start(http_port=args.port)
    serve.run(
        app, name=args.name, route_prefix=args.route_prefix
    )
    note = "" if port == args.port else " (existing proxy port kept)"
    print(
        f"serving {args.import_path} as app {args.name!r} at "
        f"http://127.0.0.1:{port}{args.route_prefix}{note}",
        flush=True,
    )
    if args.blocking:
        _run_until_signal(lambda: (serve.shutdown(), rt.shutdown()))


def cmd_serve_status(args) -> None:
    _connect(args)
    from .. import serve

    print(json.dumps(serve.status(), indent=2, default=str))


def cmd_serve_shutdown(args) -> None:
    _connect(args)
    from .. import serve

    serve.shutdown()
    print("serve shut down")


def cmd_metrics_scrape(args) -> None:
    """`ray_tpu metrics scrape` — one Prometheus text-format scrape of
    the live cluster (exactly the dashboard's /metrics payload, no
    dashboard required; pipe it to a file or promtool)."""
    _connect(args)
    from ..util.metrics import metrics_summary
    from ..util.prometheus import render_prometheus

    sys.stdout.write(render_prometheus(metrics_summary()))


def cmd_metrics_snapshot(args) -> None:
    """`ray_tpu metrics snapshot` — dump the head's time-series ring
    (bounded history of periodic metric snapshots) as JSON; --name /
    --since / --limit filter server-side."""
    _connect(args)
    from ..util.metrics import metrics_timeseries

    snapshots = metrics_timeseries(
        name=args.name, since=args.since, limit=args.limit
    )
    print(json.dumps(snapshots, indent=2, default=str))


#: `ray_tpu state ls` kinds -> state-API callables (pgs is the short
#: alias the reference CLI uses for placement groups).
_STATE_KINDS = ("nodes", "actors", "tasks", "objects", "pgs")


def cmd_state_ls(args) -> None:
    """`ray_tpu state ls {nodes,actors,tasks,objects,pgs}` — the
    state API as a CLI, following the lint/check output contract:
    `--json` emits machine-readable rows, exit code 0 on success and
    2 on usage/connection errors (argparse and _resolve_address
    already exit 2). Tasks list newest-first under --limit."""
    _connect(args)
    from ..util import state

    fetchers = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "tasks": lambda: state.list_tasks(limit=args.limit),
        "objects": lambda: state.list_objects(limit=args.limit),
        "pgs": state.list_placement_groups,
    }
    rows = fetchers[args.kind]()
    if args.as_json:
        print(json.dumps(rows, indent=2, default=str))
        return
    if not rows:
        print(f"no {args.kind}")
        return
    # Human table: union of keys, one row per entry, wide cells
    # JSON-ified (the SPA's table() in dashboard.py, terminal-ized).
    keys = []
    for row in rows:
        for key in row:
            if key not in keys:
                keys.append(key)

    def cell(value) -> str:
        if isinstance(value, (dict, list)):
            value = json.dumps(value, default=str)
        text = str(value if value is not None else "")
        return text if len(text) <= 40 else text[:37] + "..."

    table = [[cell(row.get(k)) for k in keys] for row in rows]
    widths = [
        max(len(keys[i]), *(len(r[i]) for r in table))
        for i in range(len(keys))
    ]
    print("  ".join(k.ljust(w) for k, w in zip(keys, widths)))
    for r in table:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))


def cmd_doctor(args) -> None:
    """`ray_tpu doctor` — the stall doctor. One verdict over head
    task state, per-worker in-flight views, step telemetry, and
    flight-recorder digests: stragglers, hung tasks (stacks
    auto-captured), unresponsive workers, dead nodes. Exit-code
    contract matches lint/check: 0 healthy, 1 when problems are
    found (connection/usage failures exit via argparse/sys.exit)."""
    rt = _connect(args)
    verdict = rt.diagnose(
        hung_task_s=args.hung_task_s,
        straggler_threshold=args.straggler_threshold,
        capture_stacks=not args.no_stacks,
        leak_age_s=args.leak_age_s,
        locality_miss_threshold=args.locality_miss_threshold,
    )
    if args.trace:
        # One chrome trace out of all three streams: task slices
        # (queue time split out), spans, per-rank step phases.
        from .._private.worker import global_worker

        from ..util.tracing import merge_chrome_trace

        worker = global_worker()
        merge_chrome_trace(
            worker.call("list_task_events", limit=10000)["events"],
            worker.call("list_spans", limit=10000)["spans"],
            worker.call("step_summary", limit=10000, records=True)[
                "records"
            ],
            args.trace,
        )
    problems = verdict.get("problems", [])
    if args.as_json:
        print(json.dumps(verdict, indent=2, default=str))
        sys.exit(1 if problems else 0)
    nodes = verdict.get("nodes", {})
    steps = verdict.get("steps", {})
    print(
        f"nodes: {nodes.get('alive', '?')}/{nodes.get('total', '?')} "
        "alive"
    )
    print(
        f"steps observed: {steps.get('steps_observed', 0)} "
        f"(workers reporting: {len(steps.get('workers', {}))}, "
        f"max gang skew: {steps.get('max_skew_ms', 0.0):g} ms)"
    )
    dag = verdict.get("dag") or {}
    if dag.get("edges"):
        print(f"dag edges instrumented: {len(dag['edges'])}")
        suspect = dag.get("suspect")
        if suspect:
            print(f"  {suspect['detail']}")
    rl = verdict.get("rl") or {}
    if rl.get("series"):
        series = rl["series"]
        depth = series.get("rl_queue_depth", 0)
        cap = series.get("rl_queue_capacity", 0)
        print(
            "rl dataflow: queue "
            f"{depth:g}/{cap:g}, env steps "
            f"{series.get('rl_env_steps_total', 0):g}, learner "
            f"updates {series.get('rl_learner_updates_total', 0):g}, "
            f"weight lag {series.get('rl_weight_lag', 0):g}"
        )
        print(
            f"  bottleneck [{rl.get('bottleneck', '?')}]: "
            f"{rl.get('detail', '')}"
        )
    comp = verdict.get("compile") or {}
    if comp.get("programs"):
        compiles = sum(
            row.get("compiles", 0)
            for row in comp["programs"].values()
        )
        print(
            f"xla: {compiles} compile(s) across "
            f"{len(comp['programs'])} program(s), "
            f"{len(comp.get('storms') or ())} recompile storm(s), "
            f"{len(comp.get('hbm_pressure') or ())} rank(s) under "
            "HBM pressure"
        )
    locks = verdict.get("locks") or {}
    if locks.get("enabled"):
        print(
            f"locks: witness on in {locks.get('procs', 0)} "
            f"process(es), {len(locks.get('cycles') or ())} order "
            f"inversion(s), {len(locks.get('held_blocking') or ())} "
            "held-while-blocking site(s)"
        )
    memory = verdict.get("memory") or {}
    if memory:
        print(
            "memory: "
            f"{100.0 * memory.get('attribution_fraction', 0.0):.0f}% "
            "of arena bytes attributed, "
            f"{len(memory.get('leak_suspects') or ())} leak "
            "suspect(s), "
            f"{len(memory.get('near_capacity') or ())} node(s) near "
            "capacity"
        )
    data = verdict.get("data") or {}
    hottest = data.get("hottest_flow")
    if hottest or data.get("misplaced_tasks"):
        jobs = data.get("jobs") or {}
        restore_jobs = sum(
            1
            for row in jobs.values()
            if row.get("classification") == "restore_dominated"
        )
        line = (
            "data plane: "
            f"{len(data.get('misplaced_tasks') or ())} misplaced "
            f"task class(es), {restore_jobs} restore-dominated "
            "job(s)"
        )
        if hottest:
            line += (
                "; hottest flow "
                f"{(hottest.get('src') or '?')[:12]} -> "
                f"{(hottest.get('dst') or '?')[:12]} "
                f"({hottest.get('bytes', 0) / 1e6:.1f} MB, job "
                f"{(hottest.get('job') or '-')[:8]})"
            )
        print(line)
    if verdict.get("healthy"):
        print("verdict: HEALTHY")
        return
    print(f"verdict: {len(problems)} problem(s)")
    for problem in problems:
        print(f"  [{problem.get('kind')}] {problem.get('detail')}")
        stack = problem.get("stack")
        if stack:
            print("    captured stack:")
            for line in str(stack).splitlines():
                print(f"      {line}")
    sys.exit(1)


def cmd_profile(args) -> None:
    """`ray_tpu profile` — on-demand profiling against a live
    cluster. Default (and `--job JOB`): COORDINATED GANG PROFILING —
    one synchronized window across every step-reporting rank of the
    job, merged with the gang's step-telemetry phases into one chrome
    trace (`--out`, load in chrome://tracing / Perfetto). With
    `--pid`: the single-worker profiler (cpu/stack/memory), same as
    the dashboard's /api/profile. Exit 1 when no rank could be
    captured."""
    _connect(args)
    from ..util import state

    if args.pid is not None:
        result = state.profile_worker(
            args.pid,
            kind=args.kind,
            duration_s=args.duration_s,
            hz=args.hz,
            node_id=args.node,
        )
        print(json.dumps(result, indent=2, default=str))
        return
    reply = state.profile_gang(
        args.job,
        duration_s=args.duration_s,
        hz=args.hz,
        path=args.out,
    )
    ranks = reply.get("ranks", [])
    errors = reply.get("errors", {})
    print(
        f"job {reply.get('job')}: profiled {len(ranks)} rank(s) for "
        f"{reply.get('window', {}).get('duration_s', 0):g}s, "
        f"{len(reply.get('trace', []))} trace slice(s)"
    )
    for row in ranks:
        line = (
            f"  rank {row['rank']}: {row.get('samples', 0)} samples, "
            f"{row.get('threads', 0)} thread(s)"
        )
        if row.get("jax_trace_dir"):
            line += f", jax trace: {row['jax_trace_dir']}"
        print(line)
    for rank, err in sorted(errors.items()):
        print(f"  rank {rank}: capture FAILED: {err}")
    if args.out:
        print(f"merged chrome trace: {args.out}")
    if not ranks:
        sys.exit(1)


def cmd_lint(args) -> None:
    """`ray_tpu lint [paths]` — the framework-aware distributed-
    correctness linter (devtools/lint.py, rules RT001-RT010). Runs
    offline on source trees; no cluster connection."""
    from ..devtools.lint import main as lint_main

    argv = list(args.paths or [])
    if args.as_json:
        argv.append("--json")
    if args.rules:
        argv.extend(["--rules", args.rules])
    if args.list_rules:
        argv.append("--list-rules")
    sys.exit(lint_main(argv))


def cmd_check(args) -> None:
    """`ray_tpu check [paths]` — the whole-program contract checker
    (devtools/check.py, rules RT101-RT106). Offline: builds a symbol
    table over the tree and cross-checks every .remote()/.options()/
    RPC call site; no cluster connection."""
    from ..devtools.check import main as check_main

    argv = list(args.paths or [])
    if args.as_json:
        argv.append("--json")
    if args.rules:
        argv.extend(["--rules", args.rules])
    if args.list_rules:
        argv.append("--list-rules")
    sys.exit(check_main(argv))


def cmd_race(args) -> None:
    """`ray_tpu devtools race [paths]` — whole-program concurrency
    analysis (devtools/concurrency.py, rules RT201-RT206). Offline:
    builds the thread/lock model over the tree and judges shared-state
    access; no cluster connection."""
    from ..devtools.concurrency import main as race_main

    argv = list(args.paths or [])
    if args.as_json:
        argv.append("--json")
    if args.rules:
        argv.extend(["--rules", args.rules])
    if args.list_rules:
        argv.append("--list-rules")
    sys.exit(race_main(argv))


def cmd_accel(args) -> None:
    """`ray_tpu devtools accel [paths]` — accelerator hot-path
    analysis (devtools/accel.py, rules RT301-RT306). Offline: builds
    the jit/donate wrap inventory over the tree and judges hot-loop
    usage; `--inventory` emits the machine-readable program inventory
    the compile watch's static_hint() bridge consumes."""
    from ..devtools.accel import main as accel_main

    argv = list(args.paths or [])
    if args.as_json:
        argv.append("--json")
    if args.rules:
        argv.extend(["--rules", args.rules])
    if args.list_rules:
        argv.append("--list-rules")
    if args.inventory:
        argv.append("--inventory")
    sys.exit(accel_main(argv))


def cmd_devtools_all(args) -> None:
    """`ray_tpu devtools all [paths]` — lint + check + race + accel
    as one CI gate with merged findings (devtools.all_main; JSON mode
    emits one combined list)."""
    from ..devtools import all_main

    argv = list(args.paths or [])
    if args.as_json:
        argv.append("--json")
    sys.exit(all_main(argv))


def cmd_dashboard(args) -> None:
    """Serve the dashboard against a running cluster until SIGINT /
    SIGTERM (reference: the head starts ray's dashboard; here it
    attaches to any cluster as a driver)."""
    rt = _connect(args)
    from ..dashboard import start_dashboard

    dash = start_dashboard(port=args.port)
    print(f"dashboard: http://127.0.0.1:{dash.port}", flush=True)

    def cleanup():
        dash.stop()
        rt.shutdown()

    _run_until_signal(cleanup)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="ray_tpu", description="TPU-native distributed runtime CLI"
    )
    parser.add_argument(
        "--cluster-info",
        default=DEFAULT_INFO_PATH,
        help="path of the cluster-info file (head address + pid)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_start = sub.add_parser("start", help="start a head or worker node")
    p_start.add_argument("--head", action="store_true")
    p_start.add_argument("--address", help="head address to join")
    p_start.add_argument("--num-cpus", type=float, default=None)
    p_start.add_argument("--num-tpus", type=float, default=None)
    p_start.add_argument(
        "--resources", help='extra resources as JSON, e.g. \'{"A": 2}\''
    )
    p_start.add_argument(
        "--labels",
        help="node labels as JSON (cloud startup scripts tag nodes "
        'with their provider identity, e.g. \'{"rt.io/provider-node": '
        '"my-tpu-0"}\')',
    )
    p_start.add_argument("--session-dir")
    p_start.add_argument(
        "--listen-host",
        help="bind a TCP listener on this host and advertise it "
        "cluster-wide (required for real multi-host clusters)",
    )
    p_start.add_argument("--listen-port", type=int, default=0)
    p_start.add_argument(
        "--auth-token",
        help="cluster HMAC token (defaults to RT_AUTH_TOKEN; "
        "generated for new TCP heads)",
    )
    p_start.set_defaults(fn=cmd_start)

    p_stop = sub.add_parser("stop", help="stop the head node")
    p_stop.set_defaults(fn=cmd_stop)

    for name, fn in (
        ("status", cmd_status),
        ("summary", cmd_summary),
    ):
        p = sub.add_parser(name)
        p.add_argument("--address")
        p.set_defaults(fn=fn)

    p_list = sub.add_parser("list", help="state API listings")
    p_list.add_argument(
        "kind",
        choices=[
            "nodes",
            "actors",
            "tasks",
            "objects",
            "placement-groups",
        ],
    )
    p_list.add_argument("--address")
    p_list.set_defaults(fn=cmd_list)

    p_submit = sub.add_parser("submit", help="submit a job")
    p_submit.add_argument("--address")
    p_submit.add_argument("--working-dir")
    p_submit.add_argument("--no-wait", action="store_true")
    p_submit.add_argument("--timeout", type=float, default=600.0)
    p_submit.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p_submit.set_defaults(fn=cmd_submit)

    p_jobs = sub.add_parser("jobs", help="list submitted jobs")
    p_jobs.add_argument("--address")
    p_jobs.set_defaults(fn=cmd_jobs)

    p_logs = sub.add_parser("logs", help="fetch a job's logs")
    p_logs.add_argument("job_id")
    p_logs.add_argument("--address")
    p_logs.set_defaults(fn=cmd_logs)

    p_mem = sub.add_parser(
        "memory",
        help="cluster memory ledger: usage by job/node/owner, leak "
        "suspects (exit 1 on memory findings)",
    )
    p_mem.add_argument("--address")
    p_mem.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the ledger summary as JSON (CI mode; exit 1 on "
        "memory findings)",
    )
    p_mem.add_argument(
        "-v", "--verbose", action="store_true",
        help="also print the top-owner and top-object tables",
    )
    p_mem.add_argument(
        "--transfers", action="store_true",
        help="print the cluster transfer matrix instead: per-(job, "
        "src, dst) flows, get provenance, and locality hit rates",
    )
    p_mem.set_defaults(fn=cmd_memory)

    p_tl = sub.add_parser(
        "timeline", help="export a chrome trace of task events"
    )
    p_tl.add_argument("--address")
    p_tl.add_argument("--out", default="timeline.json")
    p_tl.set_defaults(fn=cmd_timeline)

    p_up = sub.add_parser(
        "up", help="launch an autoscaling cluster from a config file"
    )
    p_up.add_argument("config", help="cluster config (.yaml or .json)")
    p_up.set_defaults(fn=cmd_up)

    p_down = sub.add_parser(
        "down", help="stop a cluster started with `up`"
    )
    p_down.set_defaults(fn=cmd_down)

    p_serve = sub.add_parser("serve", help="model-serving commands")
    serve_sub = p_serve.add_subparsers(dest="serve_cmd", required=True)
    p_srun = serve_sub.add_parser(
        "run", help="deploy module:app and serve it"
    )
    p_srun.add_argument("import_path", help="module:attr of a bound app")
    p_srun.add_argument("--address")
    p_srun.add_argument("--name", default="default")
    p_srun.add_argument("--route-prefix", default="/")
    p_srun.add_argument("--port", type=int, default=8000)
    p_srun.add_argument(
        "--non-blocking", dest="blocking", action="store_false",
        help="deploy and exit instead of serving in the foreground",
    )
    p_srun.set_defaults(fn=cmd_serve_run)
    p_sstat = serve_sub.add_parser("status", help="serve app status")
    p_sstat.add_argument("--address")
    p_sstat.set_defaults(fn=cmd_serve_status)
    p_sdown = serve_sub.add_parser(
        "shutdown", help="tear down all serve apps and proxies"
    )
    p_sdown.add_argument("--address")
    p_sdown.set_defaults(fn=cmd_serve_shutdown)

    p_metrics = sub.add_parser(
        "metrics",
        help="metrics export: Prometheus scrape / history snapshot",
    )
    metrics_sub = p_metrics.add_subparsers(
        dest="metrics_cmd", required=True
    )
    p_scrape = metrics_sub.add_parser(
        "scrape",
        help="print one Prometheus text-format scrape of the cluster",
    )
    p_scrape.add_argument("--address")
    p_scrape.set_defaults(fn=cmd_metrics_scrape)
    p_snap = metrics_sub.add_parser(
        "snapshot",
        help="dump the head's bounded metrics time-series ring (JSON)",
    )
    p_snap.add_argument("--address")
    p_snap.add_argument(
        "--name", help="filter to one metric series"
    )
    p_snap.add_argument(
        "--since", type=float, default=0.0,
        help="only snapshots newer than this unix timestamp",
    )
    p_snap.add_argument(
        "--limit", type=int, default=0,
        help="keep only the newest N snapshots",
    )
    p_snap.set_defaults(fn=cmd_metrics_snapshot)

    p_state = sub.add_parser(
        "state", help="state API listings (ls subcommand)"
    )
    state_sub = p_state.add_subparsers(dest="state_cmd", required=True)
    p_sls = state_sub.add_parser(
        "ls", help="list cluster state entities"
    )
    p_sls.add_argument("kind", choices=list(_STATE_KINDS))
    p_sls.add_argument("--address")
    p_sls.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit rows as JSON (CI/scripting mode)",
    )
    p_sls.add_argument(
        "--limit", type=int, default=1000,
        help="max rows for tasks/objects (tasks are newest-first)",
    )
    p_sls.set_defaults(fn=cmd_state_ls)

    p_prof = sub.add_parser(
        "profile",
        help="profile a gang (synchronized window, merged chrome "
        "trace) or a single worker",
    )
    p_prof.add_argument("--address")
    p_prof.add_argument(
        "--job",
        help="job id (hex) to gang-profile; default: the most "
        "recently step-reporting job",
    )
    p_prof.add_argument(
        "--pid", type=int, default=None,
        help="single-worker mode: profile this worker pid instead of "
        "a gang",
    )
    p_prof.add_argument(
        "--kind", default="cpu", choices=["cpu", "stack", "memory"],
        help="single-worker profile kind (with --pid)",
    )
    p_prof.add_argument(
        "--node", help="node id (hex) owning --pid (default: head)"
    )
    p_prof.add_argument(
        "--duration-s", type=float, default=2.0, dest="duration_s",
        help="profile window length (gang windows are capped by "
        "config profile_gang_max_duration_s)",
    )
    p_prof.add_argument("--hz", type=float, default=100.0)
    p_prof.add_argument(
        "--out", metavar="TRACE.json",
        help="write the merged gang chrome trace to this path",
    )
    p_prof.set_defaults(fn=cmd_profile)

    p_doc = sub.add_parser(
        "doctor",
        help="stall doctor: stragglers, hung tasks (with stacks), "
        "dead nodes, gang-step skew",
    )
    p_doc.add_argument("--address")
    p_doc.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the verdict as JSON (CI mode; exit 1 on problems)",
    )
    p_doc.add_argument(
        "--hung-task-s", type=float, default=None,
        help="a task with no progress past this deadline counts as "
        "hung (default: cluster config doctor_hung_task_s)",
    )
    p_doc.add_argument(
        "--straggler-threshold", type=float, default=None,
        help="a worker whose median step time exceeds cluster p50 x "
        "this factor is a straggler (default: cluster config)",
    )
    p_doc.add_argument(
        "--leak-age-s", type=float, default=None,
        help="an object held past this age by a dead owner is a "
        "leak suspect (default: cluster config doctor_leak_age_s)",
    )
    p_doc.add_argument(
        "--locality-miss-threshold", type=float, default=None,
        help="convict a task class as misplaced when at least this "
        "fraction of its get bytes pulled remotely (default: cluster "
        "config doctor_locality_miss_threshold)",
    )
    p_doc.add_argument(
        "--no-stacks", action="store_true",
        help="skip auto-capturing stack dumps of hung tasks' workers",
    )
    p_doc.add_argument(
        "--trace", metavar="OUT.json",
        help="also write a merged chrome trace (task slices + spans "
        "+ per-rank step phases) to this path",
    )
    p_doc.set_defaults(fn=cmd_doctor)

    p_lint = sub.add_parser(
        "lint",
        help="distributed-correctness linter (rules RT001-RT010)",
    )
    p_lint.add_argument(
        "paths", nargs="*", help="files/dirs to lint (default: ray_tpu)"
    )
    p_lint.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as JSON (CI mode)",
    )
    p_lint.add_argument(
        "--rules", help="comma-separated rule ids to run"
    )
    p_lint.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    p_lint.set_defaults(fn=cmd_lint)

    p_check = sub.add_parser(
        "check",
        help="whole-program contract checker (rules RT101-RT106)",
    )
    p_check.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to check as one program (default: ray_tpu)",
    )
    p_check.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as JSON (CI mode)",
    )
    p_check.add_argument(
        "--rules", help="comma-separated rule ids to run"
    )
    p_check.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    p_check.set_defaults(fn=cmd_check)

    p_devtools = sub.add_parser(
        "devtools", help="combined static-analysis gates"
    )
    devtools_sub = p_devtools.add_subparsers(
        dest="devtools_cmd", required=True
    )
    p_all = devtools_sub.add_parser(
        "all",
        help=(
            "run lint + check + race + accel with merged findings "
            "(single CI gate)"
        ),
    )
    p_all.add_argument(
        "paths", nargs="*", help="files/dirs (default: ray_tpu)"
    )
    p_all.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit merged findings as JSON (CI mode)",
    )
    p_all.set_defaults(fn=cmd_devtools_all)

    p_race = devtools_sub.add_parser(
        "race",
        help=(
            "whole-program concurrency analysis "
            "(rules RT201-RT206)"
        ),
    )
    p_race.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to analyze as one program (default: ray_tpu)",
    )
    p_race.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as JSON (CI mode)",
    )
    p_race.add_argument(
        "--rules", help="comma-separated rule ids to run"
    )
    p_race.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    p_race.set_defaults(fn=cmd_race)

    p_accel = devtools_sub.add_parser(
        "accel",
        help=(
            "accelerator hot-path analysis "
            "(rules RT301-RT306)"
        ),
    )
    p_accel.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to analyze as one program (default: ray_tpu)",
    )
    p_accel.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as JSON (CI mode)",
    )
    p_accel.add_argument(
        "--rules", help="comma-separated rule ids to run"
    )
    p_accel.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    p_accel.add_argument(
        "--inventory", action="store_true",
        help=(
            "emit the machine-readable jit-program inventory "
            "(the doctor's static_hint bridge input) instead of findings"
        ),
    )
    p_accel.set_defaults(fn=cmd_accel)

    p_dash = sub.add_parser(
        "dashboard", help="serve the dashboard for a running cluster"
    )
    p_dash.add_argument("--address")
    p_dash.add_argument("--port", type=int, default=8265)
    p_dash.set_defaults(fn=cmd_dashboard)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
