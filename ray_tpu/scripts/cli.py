"""Cluster CLI.

Reference: python/ray/scripts/scripts.py — `ray start --head` /
`ray start --address=...` bring nodes up (:644), `ray stop`, `ray
status`, `ray job submit`, and the `ray list ...` state commands
(util/state/state_cli.py). Invoked as `python -m ray_tpu <cmd>`.

The head daemon runs in the foreground of the `start` process (use
`&`, systemd, or a supervisor to daemonize); its address + pid land in
a cluster-info file (default /tmp/rt_cluster_info.json) that the other
commands read.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

DEFAULT_INFO_PATH = "/tmp/rt_cluster_info.json"


def _resolve_address(args) -> str:
    if getattr(args, "address", None):
        return args.address
    env = os.environ.get("RT_ADDRESS")
    if env:
        return env
    try:
        with open(args.cluster_info) as f:
            info = json.load(f)
        # Same-box convenience: the head recorded its TCP auth token.
        if info.get("auth_token") and not os.environ.get("RT_AUTH_TOKEN"):
            os.environ["RT_AUTH_TOKEN"] = info["auth_token"]
        return info["address"]
    except (OSError, KeyError, json.JSONDecodeError):
        sys.exit(
            "no cluster found: pass --address, set RT_ADDRESS, or "
            f"start one with `python -m ray_tpu start --head` "
            f"(looked in {args.cluster_info})"
        )


def cmd_start(args) -> None:
    import tempfile

    from .._private.accelerators import detect_accelerators
    from .._private.config import Config
    from .._private.daemon import NodeDaemon

    # TCP listeners authenticate every frame with HMAC keyed by the
    # cluster token (rpc.py). Generate one for new TCP heads so the
    # wire never runs on the well-known local key; joining nodes take
    # it from --auth-token / RT_AUTH_TOKEN / the cluster-info file.
    if getattr(args, "auth_token", None):
        os.environ["RT_AUTH_TOKEN"] = args.auth_token
    elif args.listen_host and args.head and not os.environ.get(
        "RT_AUTH_TOKEN"
    ):
        import secrets

        os.environ["RT_AUTH_TOKEN"] = secrets.token_hex(16)
        print(
            "generated cluster auth token (joining nodes need it): "
            f"RT_AUTH_TOKEN={os.environ['RT_AUTH_TOKEN']}"
        )

    config = Config.from_env(None)
    resources = json.loads(args.resources) if args.resources else {}
    resources.setdefault(
        "CPU",
        float(args.num_cpus if args.num_cpus is not None else os.cpu_count()),
    )
    detected, labels = detect_accelerators(
        {"TPU": float(args.num_tpus)} if args.num_tpus is not None else None
    )
    if getattr(args, "labels", None):
        labels.update(json.loads(args.labels))
    for name, amount in detected.items():
        resources.setdefault(name, amount)
    resources.setdefault("memory", float(2**34))
    session_dir = args.session_dir or tempfile.mkdtemp(prefix="rt_node_")
    if args.head:
        daemon = NodeDaemon(
            session_dir,
            resources,
            config,
            is_head=True,
            labels=labels,
            listen_host=args.listen_host,
            listen_port=args.listen_port,
        )
        daemon.start()
        info = {
            "address": daemon.address,
            "pid": os.getpid(),
            "session_dir": session_dir,
        }
        if args.listen_host and os.environ.get("RT_AUTH_TOKEN"):
            info["auth_token"] = os.environ["RT_AUTH_TOKEN"]
        with open(args.cluster_info, "w") as f:
            json.dump(info, f)
        print(f"head started: address={daemon.address}")
        print(
            "connect with ray_tpu.init(address="
            f"{daemon.address!r}) or RT_ADDRESS={daemon.address}"
        )
    else:
        head_address = _resolve_address(args)
        daemon = NodeDaemon(
            session_dir,
            resources,
            config,
            is_head=False,
            head_address=head_address,
            labels=labels,
            listen_host=args.listen_host,
            listen_port=args.listen_port,
        )
        daemon.start()
        print(f"node started, joined head at {head_address}")

    stop = {"flag": False}

    def on_term(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        daemon.shutdown()
        if args.head:
            try:
                os.remove(args.cluster_info)
            except OSError:
                pass


def cmd_stop(args) -> None:
    try:
        with open(args.cluster_info) as f:
            info = json.load(f)
    except OSError:
        print("no running cluster found")
        return
    try:
        os.kill(info["pid"], signal.SIGTERM)
        print(f"sent SIGTERM to head (pid {info['pid']})")
    except ProcessLookupError:
        print("head process already gone")
        try:
            os.remove(args.cluster_info)
        except OSError:
            pass


def _connect(args):
    import ray_tpu as rt

    rt.init(address=_resolve_address(args))
    return rt


def cmd_status(args) -> None:
    rt = _connect(args)
    nodes = rt.nodes()
    print(f"nodes: {len(nodes)}")
    for node in nodes:
        mark = " (head)" if node.get("is_head") else ""
        print(
            f"  {node['node_id'][:12]}{mark} alive={node['alive']} "
            f"resources={node['resources']}"
        )
    print("cluster totals:", rt.cluster_resources())
    print("available:    ", rt.available_resources())


def cmd_summary(args) -> None:
    rt = _connect(args)
    print(json.dumps(rt.state_summary(), indent=2, default=str))


def cmd_list(args) -> None:
    _connect(args)
    from ..util import state

    kind = args.kind
    rows = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "tasks": state.list_tasks,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
    }[kind]()
    print(json.dumps(rows, indent=2, default=str))


def cmd_submit(args) -> None:
    from ..job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    runtime_env = {}
    if args.working_dir:
        runtime_env["working_dir"] = args.working_dir
    import shlex

    entrypoint = list(args.entrypoint)
    if entrypoint and entrypoint[0] == "--":
        entrypoint = entrypoint[1:]
    if not entrypoint:
        sys.exit("submit: missing entrypoint command")
    job_id = client.submit_job(
        entrypoint=" ".join(shlex.quote(t) for t in entrypoint),
        runtime_env=runtime_env or None,
    )
    print(f"submitted {job_id}")
    if args.no_wait:
        return
    status = client.wait_until_finished(job_id, timeout=args.timeout)
    print(f"status: {status.value}")
    logs = client.get_job_logs(job_id)
    if logs:
        print("--- logs ---")
        print(logs, end="")
    if status != JobStatus.SUCCEEDED:
        sys.exit(1)


def cmd_jobs(args) -> None:
    from ..job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    print(json.dumps(client.list_jobs(), indent=2, default=str))


def cmd_dashboard(args) -> None:
    """Serve the dashboard against a running cluster until SIGINT /
    SIGTERM (reference: the head starts ray's dashboard; here it
    attaches to any cluster as a driver)."""
    import signal
    import time

    rt = _connect(args)
    from ..dashboard import start_dashboard

    dash = start_dashboard(port=args.port)
    print(f"dashboard: http://127.0.0.1:{dash.port}", flush=True)
    stop = {"flag": False}

    def on_term(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        dash.stop()
        rt.shutdown()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="ray_tpu", description="TPU-native distributed runtime CLI"
    )
    parser.add_argument(
        "--cluster-info",
        default=DEFAULT_INFO_PATH,
        help="path of the cluster-info file (head address + pid)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_start = sub.add_parser("start", help="start a head or worker node")
    p_start.add_argument("--head", action="store_true")
    p_start.add_argument("--address", help="head address to join")
    p_start.add_argument("--num-cpus", type=float, default=None)
    p_start.add_argument("--num-tpus", type=float, default=None)
    p_start.add_argument(
        "--resources", help='extra resources as JSON, e.g. \'{"A": 2}\''
    )
    p_start.add_argument(
        "--labels",
        help="node labels as JSON (cloud startup scripts tag nodes "
        'with their provider identity, e.g. \'{"rt.io/provider-node": '
        '"my-tpu-0"}\')',
    )
    p_start.add_argument("--session-dir")
    p_start.add_argument(
        "--listen-host",
        help="bind a TCP listener on this host and advertise it "
        "cluster-wide (required for real multi-host clusters)",
    )
    p_start.add_argument("--listen-port", type=int, default=0)
    p_start.add_argument(
        "--auth-token",
        help="cluster HMAC token (defaults to RT_AUTH_TOKEN; "
        "generated for new TCP heads)",
    )
    p_start.set_defaults(fn=cmd_start)

    p_stop = sub.add_parser("stop", help="stop the head node")
    p_stop.set_defaults(fn=cmd_stop)

    for name, fn in (
        ("status", cmd_status),
        ("summary", cmd_summary),
    ):
        p = sub.add_parser(name)
        p.add_argument("--address")
        p.set_defaults(fn=fn)

    p_list = sub.add_parser("list", help="state API listings")
    p_list.add_argument(
        "kind",
        choices=[
            "nodes",
            "actors",
            "tasks",
            "objects",
            "placement-groups",
        ],
    )
    p_list.add_argument("--address")
    p_list.set_defaults(fn=cmd_list)

    p_submit = sub.add_parser("submit", help="submit a job")
    p_submit.add_argument("--address")
    p_submit.add_argument("--working-dir")
    p_submit.add_argument("--no-wait", action="store_true")
    p_submit.add_argument("--timeout", type=float, default=600.0)
    p_submit.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p_submit.set_defaults(fn=cmd_submit)

    p_jobs = sub.add_parser("jobs", help="list submitted jobs")
    p_jobs.add_argument("--address")
    p_jobs.set_defaults(fn=cmd_jobs)

    p_dash = sub.add_parser(
        "dashboard", help="serve the dashboard for a running cluster"
    )
    p_dash.add_argument("--address")
    p_dash.add_argument("--port", type=int, default=8265)
    p_dash.set_defaults(fn=cmd_dashboard)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
