"""Attention ops: reference MHA and a Pallas TPU flash-attention kernel.

The reference framework has no fused attention of its own (it defers to
torch); for the TPU build this kernel is the MFU-critical op
(SURVEY.md §7 hard part 4). Design follows the standard TPU flash
pattern: sequential grid over KV blocks with online-softmax state in
VMEM scratch, f32 accumulation, causal block skipping, and a custom
VJP whose backward is ONE fused Pallas kernel computing dq, dk and dv
from a single s/p evaluation per tile (dq accumulates through an
aliased HBM buffer; dk/dv in VMEM scratch).

Layout: [batch, heads, seq, head_dim] with head_dim padded to 128
(MXU lane width). GQA is handled above this op by repeating KV heads.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl

try:  # TPU-only module; import lazily so CPU tests work.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret() -> bool:
    # Off-TPU the kernels run in Pallas interpreter mode, which is how
    # CI validates them numerically without hardware.
    return jax.default_backend() in ("cpu",)


def _mask_logits(s, qi, ki, block_q, block_k, causal, kv_len):
    """Mask out-of-range KV columns (sequence padded to block
    multiples) and, when causal, future positions."""
    rows = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    ) + qi * block_q
    cols = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    ) + ki * block_k
    valid = cols < kv_len
    if causal:
        valid = jnp.logical_and(valid, rows >= cols)
    return jnp.where(valid, s, DEFAULT_MASK_VALUE)


def _bias_fast_path(causal, block_q, block_k, kv_len, q_len) -> bool:
    """True when diagonal-block masking can use ONE precomputed
    additive bias tile held in VMEM scratch for the kernel's whole
    lifetime. Requires square blocks (every run&masked tile is then an
    exact diagonal with identical relative pattern: qi*bq == ki*bk ⇒
    local rows >= local cols) and no KV/Q padding. The per-tile iota/
    compare/select masking otherwise costs ~6 VPU passes over
    [block_q, block_k] — on a kernel whose MXU work is only two
    d=128-deep matmuls per tile, the VPU, not the MXU, is the
    bottleneck, and one f32 add against a resident tile is the
    cheapest mask that exists."""
    return (
        causal
        and block_q == block_k
        and kv_len % block_k == 0
        and q_len % block_q == 0
    )


def _init_bias_tile(bias_ref, first_step) -> None:
    """Fill the additive causal-mask tile (0 below/on the diagonal,
    -1e38 above) once, at the first grid step; scratch persists across
    the sequential TPU grid so every later diagonal tile reuses it."""

    @pl.when(first_step)
    def _():
        rows = jax.lax.broadcasted_iota(jnp.int32, bias_ref.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, bias_ref.shape, 1)
        bias_ref[:] = jnp.where(
            rows >= cols, 0.0, DEFAULT_MASK_VALUE
        ).astype(bias_ref.dtype)


def _block_needs_mask(qi, ki, block_q, block_k, causal, kv_len):
    """Traced predicate: does this (qi, ki) tile need logit masking?
    Returns None when masking is statically never needed, so callers
    can skip the branch entirely. Interior tiles (strictly below the
    causal diagonal, no KV padding) take the fast path — the
    iota/compare/select VPU work is a measurable cost at small
    head_dim where the VPU, not the MXU, limits the kernel."""
    may_pad = kv_len % block_k != 0  # static
    if causal:
        on_diag = qi * block_q < ki * block_k + block_k - 1
        if may_pad:
            return on_diag | (ki * block_k + block_k > kv_len)
        return on_diag
    if may_pad:
        return ki * block_k + block_k > kv_len
    return None


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Readable O(T^2)-memory attention; the numerical ground truth
    for the kernels and the CPU-test fallback."""
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t_q, t_k), dtype=bool), k=t_k - t_q)
        logits = jnp.where(mask, logits, DEFAULT_MASK_VALUE)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", weights.astype(v.dtype), v
    ).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(
    q_ref, k_ref, v_ref, out_ref, lse_ref,
    acc_ref, m_ref, l_ref, bias_ref,
    *, causal: bool, block_q: int, block_k: int,
    kv_len: int, fast_mask: bool,
):
    """Online-softmax flash forward in the log2 domain.

    q arrives PRE-SCALED by scale*log2(e) (see _flash_forward), so the
    raw QK^T dot already holds log2-domain logits: no per-tile scale
    multiply, and exp() becomes the cheaper exp2(). The VPU — not the
    MXU — limits this kernel at head_dim 128 (two d=128 matmuls per
    [bq, bk] tile vs ~4 elementwise passes over it), so every saved
    full-tile pass is ~10% of kernel time. lse is emitted in the SAME
    log2 domain; the backward kernels consume it symmetrically."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    if fast_mask:
        _init_bias_tile(
            bias_ref,
            (pl.program_id(0) == 0) & (qi == 0) & (ki == 0),
        )

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: skip fully-masked KV blocks (q rows all before kv cols).
    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= ki * block_k

    def _update(s, v):
        m_prev = m_ref[:, :1]  # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s - m_new)  # [bq, bk] f32
        alpha = jnp.exp2(m_prev - m_new)  # [bq, 1]
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(run)
    def _compute():
        # MXU dots stay in the input dtype (bf16) with f32 accumulation
        # via preferred_element_type — upcasting operands to f32 first
        # would run the matmuls at a fraction of the bf16 MXU rate.
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk] f32, log2-domain logits

        needs_mask = _block_needs_mask(
            qi, ki, block_q, block_k, causal, kv_len
        )
        if needs_mask is None:
            _update(s, v)
        elif fast_mask:
            # Square blocks: the only run&masked tiles are exact
            # diagonals — one resident additive tile masks them all.
            @pl.when(needs_mask)
            def _masked():
                _update(s + bias_ref[:], v)

            @pl.when(jnp.logical_not(needs_mask))
            def _interior():
                _update(s, v)
        else:
            @pl.when(needs_mask)
            def _masked():
                _update(
                    _mask_logits(
                        s, qi, ki, block_q, block_k, causal, kv_len
                    ),
                    v,
                )

            @pl.when(jnp.logical_not(needs_mask))
            def _interior():
                _update(s, v)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out_ref[0] = (acc_ref[:] / l_safe).astype(out_ref.dtype)
        # lse rides in an 8-sublane layout (TPU block shapes need the
        # second-to-last dim divisible by 8). Log2 domain, like m.
        row = m_ref[:, 0] + jnp.log2(l_safe[:, 0])  # [bq]
        lse_ref[0] = jnp.broadcast_to(row[None, :], lse_ref.shape[1:])


#: Pre-scaling constant: folding softmax scale AND log2(e) into q turns
#: the per-tile `s * scale` pass + natural exp into a bare dot + exp2.
_LOG2E = math.log2(math.e)


def _flash_forward(q, k, v, scale, causal, block_q, block_k, kv_len):
    bh, t, d = q.shape
    tk = k.shape[1]
    nq = pl.cdiv(t, block_q)
    nk = pl.cdiv(tk, block_k)
    grid = (bh, nq, nk)
    fast_mask = _bias_fast_path(causal, block_q, block_k, kv_len, t)
    kernel = functools.partial(
        _fwd_kernel,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        kv_len=kv_len,
        fast_mask=fast_mask,
    )
    # XLA fuses this multiply into q's producer; inside the kernel it
    # would cost a pass per (qi, ki) tile instead of one per qi block.
    # f32 multiply then cast: the effective logit scale stays exact
    # (only the usual bf16 storage rounding), where a bf16*bf16
    # multiply would perturb the softmax temperature itself.
    q2 = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            # bf16: halves the tile's scoped-VMEM footprint — the f32
            # version pushed the 1024x1024 fwd config 292K past the
            # 16M scoped limit. 0 and -1e38 are both exact in bf16.
            pltpu.VMEM(
                (block_q, block_k) if fast_mask else (8, 128),
                jnp.bfloat16,
            ),
        ],
        interpret=_interpret(),
    )(q2, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_fused_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_in_ref,
    dq_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref, bias_ref, dq_all_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    kv_len: int, q_len: int, fast_mask: bool, interp: bool,
):
    """Single-pass backward: dq, dk, dv from ONE s/p computation per
    tile. Split dq + dkv kernels would each recompute s = q2 @ k^T and
    p = exp2(s - lse) — 2 of 7 MXU passes and ~40% of the VPU work
    duplicated. The grid is kv-major (dk/dv accumulate in VMEM
    scratch); dq instead accumulates through an ALIASED HBM buffer
    (dq_in -> dq, f32): its (b, qi) block is revisited
    non-consecutively across ki, so each visit adds this tile's
    contribution. Tiles skipped by the causal test copy the partial
    sum through (the output block is emitted every step regardless).

    Chain-rule factor placement (q2 = scale * log2e * q, lse in the
    log2 domain, so p = exp2(s - lse) equals the natural-domain
    softmax exactly):
      dv = p^T @ do                      — exact as accumulated;
      ds = p * (dp - delta)              — natural-domain ds/scale;
      dq = sum_k (ds @ k) * scale        — scale per [bq, d] tile;
      dk = (sum_q ds^T @ q2) * ln2       — ln2 * log2e == 1 restores
                                           scale * ds^T @ q at the
                                           final store."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    nk = pl.num_programs(1)

    if fast_mask:
        _init_bias_tile(
            bias_ref,
            (pl.program_id(0) == 0) & (ki == 0) & (qi == 0),
        )

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    if interp:
        # Interpreter mode does not preserve written output blocks
        # across non-consecutive revisits (the aliased-HBM dq
        # accumulation below reads back stale input instead), so CPU
        # validation accumulates dq in a full-size scratch — fine at
        # test shapes, unaffordable at real sequence lengths.
        @pl.when((ki == 0) & (qi == 0))
        def _init_dq_all():
            dq_all_ref[:] = jnp.zeros_like(dq_all_ref)

    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][0][:, None]  # log2 domain
        delta = delta_ref[0][0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        def _update(p):
            pb = p.astype(do.dtype)
            dv_acc_ref[:] += jax.lax.dot_general(
                pb, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = (p * (dp - delta)).astype(q.dtype)  # [bq, bk]
            dk_acc_ref[:] += jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # `scale` applied per TILE on the small [bq, d] result
            # (ds itself omits it — see kernel docstring), so the
            # running dq sum is always final-scaled: no last-tile
            # bookkeeping, and the TPU and interpreter accumulation
            # schemes stay numerically identical.
            dq_tile = jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if interp:
                sl = pl.dslice(qi * block_q, block_q)
                dq_all_ref[sl, :] += dq_tile
                dq_ref[0] = dq_all_ref[sl, :]
            else:
                prev = jnp.where(ki == 0, 0.0, dq_in_ref[0])
                dq_ref[0] = prev + dq_tile

        def _row_masked(p):
            # Padded q rows (beyond q_len) must not contribute.
            row_ids = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            ) + qi * block_q
            return jnp.where(row_ids < q_len, p, 0.0)

        needs_mask = _block_needs_mask(
            qi, ki, block_q, block_k, causal, kv_len
        )
        q_may_pad = q_len % block_q != 0  # static
        if q_may_pad:
            row_mask = qi == nq - 1
            needs_mask = (
                row_mask if needs_mask is None else needs_mask | row_mask
            )
        if needs_mask is None:
            _update(jnp.exp2(s - lse))
        elif fast_mask:
            @pl.when(needs_mask)
            def _masked():
                _update(jnp.exp2(s + bias_ref[:] - lse))

            @pl.when(jnp.logical_not(needs_mask))
            def _interior():
                _update(jnp.exp2(s - lse))
        else:
            @pl.when(needs_mask)
            def _masked():
                p = jnp.exp2(_mask_logits(
                    s, qi, ki, block_q, block_k, causal, kv_len
                ) - lse)
                _update(_row_masked(p) if q_may_pad else p)

            @pl.when(jnp.logical_not(needs_mask))
            def _interior():
                _update(jnp.exp2(s - lse))

    @pl.when(jnp.logical_not(run))
    def _passthrough():
        # Skipped causal tiles still emit the dq block: carry the
        # partial (already per-tile-scaled) sum forward unchanged.
        if interp:
            sl = pl.dslice(qi * block_q, block_q)
            dq_ref[0] = dq_all_ref[sl, :]
        else:
            dq_ref[0] = dq_in_ref[0]

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = (dk_acc_ref[:] * math.log(2.0)).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _flash_backward_fused(
    q, k, v, out, lse, do, scale, causal, block_q, block_k, kv_len, q_len
):
    bh, t, d = q.shape
    tk = k.shape[1]
    # f32 intermediates (s, p, dp, ds) plus three accumulators cap the
    # square tile at 512 under the 16 MiB scoped-VMEM budget. Square,
    # so the diagonal-bias fast path applies (_bias_fast_path).
    block_q = min(block_q, 512)
    block_k = min(block_k, 512)
    nq = pl.cdiv(t, block_q)
    nk = pl.cdiv(tk, block_k)
    fast_mask = _bias_fast_path(causal, block_q, block_k, kv_len, q_len)
    q2 = (q.astype(jnp.float32) * (scale * _LOG2E)).astype(q.dtype)
    delta = jnp.sum(
        out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1
    )  # [bh, t]
    delta = jnp.broadcast_to(delta[:, None, :], (bh, 8, t))
    bias_scratch = pltpu.VMEM(
        (block_q, block_k) if fast_mask else (8, 128), jnp.bfloat16
    )
    # dq accumulator rides in HBM through an aliased input/output pair
    # (its blocks are revisited across ki). Never read at ki == 0;
    # jnp.zeros still materializes a fill (JAX has no uninitialized
    # arrays — ~64 MB/step at bench shapes, ~0.5% of step time), which
    # the alias donates back to the output. Alias-revisit coherency
    # (including the consecutive-revisit nq==1 case) is validated on
    # hardware by the cross-attention grad shapes in the verify
    # recipe — interpret mode cannot model it (see _bwd_fused_kernel).
    dq_seed = jnp.zeros((bh, t, d), jnp.float32)

    interp = _interpret()
    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_fused_kernel,
            scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
            kv_len=kv_len, q_len=q_len, fast_mask=fast_mask,
            interp=interp,
        ),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
            bias_scratch,
            # Full-size dq scratch only for interpreter-mode CPU
            # validation (see _bwd_fused_kernel); token-size on TPU.
            pltpu.VMEM(
                (nq * block_q, d) if interp else (8, 128), jnp.float32
            ),
        ],
        input_output_aliases={6: 0},
        interpret=interp,
    )(q2, k, v, do, lse, delta, dq_seed)
    return dq.astype(q.dtype), dk, dv


# ---------------------------------------------------------------------------
# public op with custom VJP
# ---------------------------------------------------------------------------

def _on_tpu() -> bool:
    try:
        return jax.default_backend() not in ("cpu", "gpu") and pltpu is not None
    except Exception:
        return False


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8)
)
def _flash_attention_bhsd(
    q, k, v, scale, causal, block_q, block_k, kv_len, q_len
):
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k, kv_len)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k, kv_len, q_len):
    out, lse = _flash_forward(
        q, k, v, scale, causal, block_q, block_k, kv_len
    )
    # Residuals carry checkpoint names so a remat policy that saves
    # them (models.llama remat_policy="dots_flash") turns the backward
    # recompute of this kernel into a table lookup: without the names,
    # jax.checkpoint re-RUNS the whole forward flash kernel inside the
    # backward pass just to rebuild (out, lse) — measured at ~15% of
    # the 410M bench step (2.7ms/layer fwd kernel x 24 layers).
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(
    scale, causal, block_q, block_k, kv_len, q_len, residuals, do
):
    q, k, v, out, lse = residuals
    dq, dk, dv = _flash_backward_fused(
        q, k, v, out, lse, do, scale, causal, block_q, block_k,
        kv_len, q_len,
    )
    return dq, dk, dv


_flash_attention_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    force_pallas: Optional[bool] = None,
) -> jax.Array:
    """Fused attention: Pallas kernel on TPU, reference math elsewhere.

    q/k/v: [batch, heads, seq, head_dim]. head_dim should be a
    multiple of 128 for MXU efficiency (callers pad).
    """
    b, h, t, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    use_pallas = _on_tpu() if force_pallas is None else force_pallas
    if not use_pallas:
        return mha_reference(q, k, v, causal=causal, scale=scale)
    tk = k.shape[2]
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, tk, d)
    vf = v.reshape(b * h, tk, d)
    # Pad sequences to block multiples with defined zeros; kernels mask
    # columns >= tk (and padded rows in the dk/dv pass), and the q
    # padding is sliced off the output.
    t_pad = -t % block_q
    tk_pad = -tk % block_k
    if t_pad:
        qf = jnp.pad(qf, ((0, 0), (0, t_pad), (0, 0)))
    if tk_pad:
        kf = jnp.pad(kf, ((0, 0), (0, tk_pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, tk_pad), (0, 0)))
    out = _flash_attention_bhsd(
        qf, kf, vf, scale, causal, block_q, block_k, tk, t
    )
    return out[:, :t, :].reshape(b, h, t, d)


def repeat_kv(k: jax.Array, num_rep: int) -> jax.Array:
    """Expand KV heads for grouped-query attention: [b, kvh, t, d] →
    [b, kvh*num_rep, t, d]."""
    if num_rep == 1:
        return k
    b, kvh, t, d = k.shape
    return jnp.repeat(k, num_rep, axis=1)
