"""Ring attention: exact attention over sequence shards on a ring.

Net-new relative to the reference, which has no sequence/context
parallelism at all (SURVEY.md §5.7 — verified absent; its nearest
primitives are NCCL p2p send/recv in util.collective). Here the ring
rides the ICI mesh axis: each step computes blockwise attention of the
local Q shard against the currently-held KV shard while `ppermute`
rotates KV shards around the ring, merging partial results with the
online-softmax rule — memory stays O(T_local^2 / ring) per step and KV
transfer overlaps compute under XLA's scheduler.

Use inside `shard_map` with the sequence dimension sharded over
`axis_name` ("sp"), contiguous layout: rank r owns positions
[r*T_local, (r+1)*T_local).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.collective import axis_size as _axis_size

from .attention import DEFAULT_MASK_VALUE


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over a sequence-sharded ring.

    q/k/v: local shards [batch, heads, t_local, head_dim].
    Returns the local output shard [batch, heads, t_local, head_dim].
    """
    b, h, t_local, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    n = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)

    qf = q.astype(jnp.float32)
    q_pos = rank * t_local + jnp.arange(t_local)  # global positions

    # Receive-from-left permutation: after s steps we hold the KV shard
    # of rank (rank - s) mod n.
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (rank - s) % n
        k_pos = src * t_local + jnp.arange(t_local)
        logits = (
            jnp.einsum(
                "bhqd,bhkd->bhqk",
                qf,
                k_cur.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, DEFAULT_MASK_VALUE)
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd",
            p,
            v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        # Rotate KV shards one step around the ring (ICI neighbor hop).
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return acc_new, m_new, l_new, k_next, v_next

    # Initializers are device-varying over the ring axis (each rank
    # accumulates different data) — mark them so scan's
    # varying-manual-axes type check agrees (jax >= 0.7).
    def _varying(x):
        try:
            return lax.pcast(x, (axis_name,), to="varying")
        except (AttributeError, TypeError, ValueError):
            # Already varying over axis_name (the *_like inits inherit
            # it from q), or an older jax without pcast.
            return x

    # *_like inherits every OTHER varying axis q already carries (pp/ep
    # when ring attention runs inside the pipeline/MoE composition).
    acc = _varying(jnp.zeros_like(qf))
    m = _varying(jnp.full_like(qf[..., :1], -jnp.inf))
    l = _varying(jnp.zeros_like(qf[..., :1]))
    acc, m, l, _, _ = lax.fori_loop(0, n, step, (acc, m, l, k, v))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    attention_fn=None,
) -> jax.Array:
    """Ulysses-style sequence parallelism: all-to-all from seq-sharded
    to head-sharded, run full-sequence attention locally on the head
    subset, all-to-all back (SURVEY.md §2.4 SP row).

    Requires heads % axis_size == 0. q/k/v: [batch, heads, t_local, d].
    """
    from .attention import mha_reference

    attention_fn = attention_fn or (
        lambda q, k, v: mha_reference(q, k, v, causal=causal, scale=scale)
    )
    n = _axis_size(axis_name)

    def reshard_to_heads(x):
        # [b, H, t/n, d] -> [b, H/n, t, d]: split heads, concat seq.
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def reshard_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = map(reshard_to_heads, (q, k, v))
    out = attention_fn(qh, kh, vh)
    return reshard_to_seq(out)
