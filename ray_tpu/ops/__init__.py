"""TPU compute ops: fused attention kernels, ring/Ulysses sequence
parallelism, norms, and rotary embeddings."""

from .attention import flash_attention, mha_reference, repeat_kv
from .norms import apply_rotary, rms_norm, rotary_embedding, swiglu
from .ring_attention import ring_attention, ulysses_attention

__all__ = [
    "flash_attention",
    "mha_reference",
    "repeat_kv",
    "rms_norm",
    "rotary_embedding",
    "apply_rotary",
    "swiglu",
    "ring_attention",
    "ulysses_attention",
]
