"""Mixture-of-experts with expert parallelism.

Absent from the reference (SURVEY.md §2.4 EP row — no MoE sharding
anywhere in Ray core or its libraries); built TPU-first: experts shard
over the `ep` mesh axis, token dispatch/return are `lax.all_to_all`
hops over ICI, and the per-expert FFN is a dense batched matmul that
lands on the MXU (GShard/Switch capacity-based dispatch — fixed
capacity keeps every shape static for XLA; overflow tokens drop to the
residual path, the standard trade).
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.collective import axis_size as _axis_size


def init_moe_params(
    key,
    num_experts: int,
    d_model: int,
    d_ff: int,
    dtype=jnp.float32,
) -> Dict[str, jax.Array]:
    k_router, k1, k2 = jax.random.split(key, 3)
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_ff)
    return {
        "router": (
            jax.random.normal(k_router, (d_model, num_experts)) * scale_in
        ).astype(dtype),
        "w_in": (
            jax.random.normal(k1, (num_experts, d_model, d_ff)) * scale_in
        ).astype(dtype),
        "w_out": (
            jax.random.normal(k2, (num_experts, d_ff, d_model)) * scale_out
        ).astype(dtype),
    }


def top_k_router(
    logits: jax.Array, k: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """[tokens, experts] -> (gates [t, k], indices [t, k], aux_loss).

    aux_loss is the Switch/GShard load-balancing loss: mean expert
    probability x mean assignment fraction, scaled by num_experts.
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gates, indices = lax.top_k(probs, k)
    gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    num_experts = logits.shape[-1]
    assign = jnp.sum(
        jax.nn.one_hot(indices[:, 0], num_experts), axis=0
    ) / logits.shape[0]
    importance = jnp.mean(probs, axis=0)
    aux_loss = num_experts * jnp.sum(assign * importance)
    return gates, indices, aux_loss


def _dispatch_tensors(
    indices: jax.Array,
    gates: jax.Array,
    num_experts: int,
    capacity: int,
):
    """Capacity-based dispatch (Switch-style): per (token, choice),
    its position in the target expert's buffer; tokens past capacity
    drop. Returns dispatch one-hot [t, E, C] and combine [t, E, C]."""
    t, k = indices.shape
    flat_expert = indices.reshape(-1)  # [t*k], choice-major rows
    onehot = jax.nn.one_hot(flat_expert, num_experts, dtype=jnp.int32)
    # Position of each (token, choice) within its expert queue.
    position = jnp.cumsum(onehot, axis=0) * onehot - 1  # [t*k, E]
    pos_in_expert = jnp.sum(position * onehot, axis=-1)  # [t*k]
    keep = pos_in_expert < capacity
    pos_clipped = jnp.clip(pos_in_expert, 0, capacity - 1)
    dispatch = (
        jax.nn.one_hot(flat_expert, num_experts)[:, :, None]
        * jax.nn.one_hot(pos_clipped, capacity)[:, None, :]
        * keep[:, None, None]
    )  # [t*k, E, C]
    dispatch = dispatch.reshape(t, k, num_experts, capacity).sum(axis=1)
    combine = (
        (
            jax.nn.one_hot(flat_expert, num_experts)[:, :, None]
            * jax.nn.one_hot(pos_clipped, capacity)[:, None, :]
            * (keep * gates.reshape(-1))[:, None, None]
        )
        .reshape(t, k, num_experts, capacity)
        .sum(axis=1)
    )
    return dispatch, combine


def moe_ffn_dense(params: Dict, x: jax.Array, k: int = 2):
    """Single-device reference: every expert local. x: [tokens, d]."""
    logits = x @ params["router"]
    gates, indices, aux = top_k_router(logits, k)
    outs = jnp.einsum("td,edf->tef", x, params["w_in"])
    outs = jax.nn.gelu(outs)
    outs = jnp.einsum("tef,efd->ted", outs, params["w_out"])
    picked = jnp.take_along_axis(
        outs, indices[:, :, None], axis=1
    )  # [t, k, d]
    return (
        jnp.sum(picked * gates[:, :, None].astype(x.dtype), axis=1),
        aux,
    )


def moe_ffn_ep(
    params: Dict,
    x: jax.Array,
    *,
    axis_name: str = "ep",
    k: int = 2,
    capacity_factor: float = 2.0,
):
    """Expert-parallel MoE inside shard_map.

    Each rank holds E_local = E/ep experts (params sharded on the
    expert axis) and a token shard x: [t_local, d]. Dispatch:
    one all_to_all sends each rank's per-expert buffers to the expert's
    owner; experts run dense; a second all_to_all returns outputs.
    """
    ep = _axis_size(axis_name)
    e_local = params["w_in"].shape[0]
    num_experts = e_local * ep
    t_local, d = x.shape
    capacity = int(
        math.ceil(k * t_local * capacity_factor / num_experts)
    )
    capacity = max(capacity, 1)

    # The router is tiny ([d, E]) and replicated on every rank; only
    # the expert FFN weights shard over ep.
    logits = x @ params["router"]
    gates, indices, aux = top_k_router(logits, k)
    dispatch, combine = _dispatch_tensors(
        indices, gates, num_experts, capacity
    )
    # Expert-major buffers: [E, C, d] = tokens this rank sends to each
    # expert, then all_to_all regroups by owner rank.
    expert_inputs = jnp.einsum(
        "tec,td->ecd", dispatch.astype(x.dtype), x
    )  # [E, C, d]
    # [E, C, d] -> [ep, E_local, C, d] -> a2a -> [ep, E_local, C, d]
    # where now the leading axis indexes SOURCE rank.
    expert_inputs = expert_inputs.reshape(ep, e_local, capacity, d)
    expert_inputs = lax.all_to_all(
        expert_inputs, axis_name, split_axis=0, concat_axis=0, tiled=True
    ).reshape(ep, e_local, capacity, d)
    # Local experts over all source ranks' buffers: [E_local, ep*C, d].
    h = expert_inputs.transpose(1, 0, 2, 3).reshape(
        e_local, ep * capacity, d
    )
    h = jnp.einsum("ecd,edf->ecf", h, params["w_in"])
    h = jax.nn.gelu(h)
    h = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    # Return trip: back to source ranks.
    h = h.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
    h = lax.all_to_all(
        h, axis_name, split_axis=0, concat_axis=0, tiled=True
    ).reshape(num_experts, capacity, d)
    out = jnp.einsum(
        "tec,ecd->td", combine.astype(h.dtype), h
    )
    return out.astype(x.dtype), aux
