"""Normalization and positional-embedding ops.

Pure-JAX implementations: XLA fuses these elementwise chains into the
surrounding matmuls on TPU, so a hand-written kernel buys nothing
(unlike attention, where the O(T^2) intermediate forces the fused
Pallas kernel in attention.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6,
             offset: float = 0.0) -> jax.Array:
    """RMSNorm in f32 accumulation, cast back to the input dtype.
    `offset` supports the Gemma convention of scaling by (1 + w)
    (the checkpoint stores w near zero)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    scale = weight.astype(jnp.float32)
    if offset:
        scale = scale + offset
    return (normed * scale).astype(dtype)


def rope_frequencies(
    head_dim: int, theta: float = 10000.0, scaling=None
) -> jax.Array:
    """Per-dimension RoPE inverse frequencies, optionally rescaled.

    `scaling` is None or a tuple
    `(kind, factor, low_freq_factor, high_freq_factor, original_max)`:

    - "linear": every frequency divided by `factor` (position
      interpolation).
    - "llama3": Llama-3.1's piecewise scheme (public formula; HF
      modeling_rope_utils._compute_llama3_parameters): wavelengths
      shorter than original_max/high_freq_factor keep their frequency,
      longer than original_max/low_freq_factor divide by `factor`, and
      the band between interpolates smoothly.
    """
    half = head_dim // 2
    freqs = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    if scaling is None:
        return freqs
    kind, factor, low_ff, high_ff, orig_max = scaling
    if kind == "linear":
        return freqs / factor
    if kind == "llama3":
        low_wavelen = orig_max / low_ff
        high_wavelen = orig_max / high_ff
        wavelen = 2.0 * jnp.pi / freqs
        smooth = (orig_max / wavelen - low_ff) / (high_ff - low_ff)
        smoothed = (1.0 - smooth) * freqs / factor + smooth * freqs
        return jnp.where(
            wavelen > low_wavelen,
            freqs / factor,
            jnp.where(wavelen < high_wavelen, freqs, smoothed),
        )
    raise ValueError(f"unknown rope scaling kind {kind!r}")


def rotary_embedding(
    positions: jax.Array,
    head_dim: int,
    theta: float = 10000.0,
    scaling=None,
):
    """Rotary position embedding tables: returns (cos, sin) of shape
    [*positions.shape, head_dim // 2], f32."""
    freqs = rope_frequencies(head_dim, theta, scaling)
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(
    x: jax.Array, cos: jax.Array, sin: jax.Array
) -> jax.Array:
    """Apply RoPE to [batch, heads, seq, head_dim] given per-position
    (cos, sin) of shape [batch, seq, head_dim//2] (or broadcastable)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = xf[..., :half], xf[..., half:]
    # cos/sin: [b, t, half] -> [b, 1, t, half] to broadcast over heads.
    if cos.ndim == 3:
        cos = cos[:, None, :, :]
        sin = sin[:, None, :, :]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(dtype)


def swiglu(x: jax.Array, gate: jax.Array) -> jax.Array:
    """SwiGLU activation: silu(gate) * x."""
    return jax.nn.silu(gate) * x
