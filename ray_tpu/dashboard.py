"""Dashboard-lite: JSON/HTML cluster introspection over HTTP.

Reference: python/ray/dashboard — an aiohttp head serving a React SPA
plus per-node agents. The TPU-native rebuild keeps the data plane (the
state API the SPA consumes) and serves it as JSON endpoints + a
self-contained HTML page + a Prometheus text endpoint, from a stdlib
HTTP thread on the driver or via `python -m ray_tpu dashboard`.

Endpoints: /            — HTML summary page (auto-refreshing)
           /api/summary — state summary
           /api/nodes | /api/actors | /api/tasks | /api/objects
           /api/placement_groups | /api/resources | /api/metrics
           /metrics     — Prometheus exposition text
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta http-equiv="refresh" content="2">
<style>body{font-family:monospace;margin:2em}table{border-collapse:
collapse}td,th{border:1px solid #999;padding:4px 8px;text-align:left}
h2{margin-top:1.5em}</style></head>
<body><h1>ray_tpu cluster</h1><div id="content">%CONTENT%</div>
</body></html>"""


def _render_table(rows) -> str:
    if not rows:
        return "<i>none</i>"
    keys = list(rows[0].keys())
    head = "".join(f"<th>{k}</th>" for k in keys)
    body = "".join(
        "<tr>"
        + "".join(f"<td>{row.get(k, '')}</td>" for k in keys)
        + "</tr>"
        for row in rows
    )
    return f"<table><tr>{head}</tr>{body}</table>"


def _prometheus_text(metrics: dict) -> str:
    lines = []
    for name, entry in metrics.items():
        kind = entry.get("kind")
        safe = name.replace(".", "_").replace("-", "_")
        if kind == "counter":
            lines.append(f"# TYPE {safe} counter")
            lines.append(f"{safe} {entry.get('total', 0.0)}")
        elif kind == "gauge":
            lines.append(f"# TYPE {safe} gauge")
            lines.append(f"{safe} {entry.get('value', 0.0)}")
        else:
            lines.append(f"# TYPE {safe} summary")
            lines.append(f"{safe}_count {entry.get('count', 0)}")
            lines.append(f"{safe}_sum {entry.get('sum', 0.0)}")
    return "\n".join(lines) + "\n"


class Dashboard:
    def __init__(self, port: int = 8265):
        from .util import state as state_api

        self._state = state_api
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_GET(self):
                try:
                    status, payload, ctype = dashboard._route(self.path)
                except Exception as e:  # noqa: BLE001 — 500 surface
                    status = 500
                    payload = json.dumps({"error": repr(e)}).encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def _collect(self, kind: str):
        import ray_tpu

        state = self._state
        return {
            "summary": lambda: ray_tpu.state_summary(),
            "nodes": state.list_nodes,
            "actors": state.list_actors,
            "tasks": state.list_tasks,
            "objects": state.list_objects,
            "placement_groups": state.list_placement_groups,
            "resources": lambda: {
                "total": ray_tpu.cluster_resources(),
                "available": ray_tpu.available_resources(),
            },
            "metrics": self._metrics,
        }[kind]()

    @staticmethod
    def _metrics():
        from .util.metrics import metrics_summary

        return metrics_summary()

    def _route(self, path: str):
        if path.startswith("/api/"):
            kind = path[len("/api/") :].strip("/")
            data = self._collect(kind)
            return (
                200,
                json.dumps(data, default=str).encode(),
                "application/json",
            )
        if path == "/metrics":
            return (
                200,
                _prometheus_text(self._metrics()).encode(),
                "text/plain; version=0.0.4",
            )
        if path in ("/", "/index.html"):
            import ray_tpu

            sections = [
                "<h2>summary</h2>"
                + _render_table([ray_tpu.state_summary()]),
                "<h2>resources</h2>"
                + _render_table(
                    [
                        {
                            "total": ray_tpu.cluster_resources(),
                            "available": ray_tpu.available_resources(),
                        }
                    ]
                ),
                "<h2>nodes</h2>"
                + _render_table(self._state.list_nodes()),
                "<h2>actors</h2>"
                + _render_table(self._state.list_actors()),
                "<h2>placement groups</h2>"
                + _render_table(self._state.list_placement_groups()),
            ]
            page = _PAGE.replace("%CONTENT%", "".join(sections))
            return 200, page.encode(), "text/html"
        return (
            404,
            json.dumps({"error": "not found"}).encode(),
            "application/json",
        )

    def stop(self) -> None:
        self._server.shutdown()


def start_dashboard(port: int = 8265) -> Dashboard:
    """Serve the dashboard from this (driver) process."""
    return Dashboard(port)
