"""Dashboard-lite: JSON/HTML cluster introspection over HTTP.

Reference: python/ray/dashboard — an aiohttp head serving a React SPA
plus per-node agents. The TPU-native rebuild keeps the data plane (the
state API the SPA consumes) and serves it as JSON endpoints + a
self-contained HTML page + a Prometheus text endpoint, from a stdlib
HTTP thread on the driver or via `python -m ray_tpu dashboard`.

Endpoints: /            — HTML summary page (auto-refreshing)
           /api/summary — state summary
           /api/nodes | /api/actors | /api/tasks | /api/objects
           /api/placement_groups | /api/resources | /api/metrics
           /api/serve   — per-deployment serving stats (p50/p99,
                          in-flight, queue depth)
           /api/memory  — cluster memory ledger (per-job/-owner
                          attribution, leak suspects, verdict.memory)
           /api/transfers — data-plane transfer matrix (per-(job,
                          src, dst) flows, get provenance, locality
                          hit rates, top remote-pulling task classes)
           /api/timeseries?name=...&since=...&limit=...
                        — head snapshot-ring history
           /metrics     — Prometheus exposition text (0.0.4)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

#: Self-contained SPA (no build step, no external assets — the
#: reference ships a React bundle; this serves the same state API from
#: one static page with fetch polling).
_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><meta charset="utf-8">
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1c2430}
 header{background:#1c2430;color:#fff;padding:10px 20px;display:flex;
   align-items:baseline;gap:16px}
 header h1{font-size:18px;margin:0}
 header span{color:#9fb0c3;font-size:12px}
 nav{display:flex;gap:4px;padding:8px 16px;background:#fff;
   border-bottom:1px solid #dde3ea}
 nav button{border:0;background:none;padding:6px 12px;cursor:pointer;
   border-radius:6px;font-size:13px;color:#44506a}
 nav button.active{background:#e8eefc;color:#1a48c4;font-weight:600}
 main{padding:16px 20px}
 .cards{display:flex;gap:12px;flex-wrap:wrap;margin-bottom:16px}
 .card{background:#fff;border:1px solid #dde3ea;border-radius:8px;
   padding:10px 16px;min-width:110px}
 .card .v{font-size:22px;font-weight:700}
 .card .k{font-size:11px;color:#7a8699;text-transform:uppercase}
 table{border-collapse:collapse;background:#fff;width:100%;font-size:13px}
 td,th{border:1px solid #e3e8ef;padding:6px 10px;text-align:left}
 th{background:#eef1f6;font-weight:600}
 tr:nth-child(even) td{background:#fafbfd}
 #err{color:#b00020;font-size:12px}
 code{font-size:12px}
</style></head><body>
<header><h1>ray_tpu</h1><span id="addr"></span><span id="err"></span></header>
<nav id="tabs"></nav><main>
 <div class="cards" id="cards"></div>
 <div id="view"></div>
</main>
<script>
const TABS = ["nodes","actors","tasks","objects","memory","transfers",
              "placement_groups","resources","metrics","serve",
              "spans","steps","compile","doctor"];
let active = "nodes";
const $ = (id) => document.getElementById(id);
function tabs() {
  $("tabs").innerHTML = TABS.map(t =>
    `<button class="${t===active?"active":""}"
       onclick="active='${t}';tabs();tick()">${t.replace("_"," ")}</button>`
  ).join("");
}
function esc(s) {
  return String(s).replace(/[&<>"']/g, c => ({"&":"&amp;","<":"&lt;",
    ">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
}
function table(rows) {
  if (!Array.isArray(rows)) rows = rows ? [rows] : [];
  if (!rows.length) return "<i>none</i>";
  // Union of keys: rows can be heterogeneous (metrics kinds).
  const keys = [...new Set(rows.flatMap(r => Object.keys(r)))];
  const fmt = v => v === undefined ? ""
    : typeof v === "object" && v !== null
      ? `<code>${esc(JSON.stringify(v))}</code>` : esc(v);
  return "<table><tr>" + keys.map(k=>`<th>${esc(k)}</th>`).join("") +
    "</tr>" + rows.map(r => "<tr>" +
      keys.map(k=>`<td>${fmt(r[k])}</td>`).join("") + "</tr>").join("") +
    "</table>";
}
async function j(path) {
  const resp = await fetch(path);
  if (!resp.ok) throw new Error(path + " -> HTTP " + resp.status);
  return resp.json();
}
async function tick() {
  const tab = active;  // discard stale responses after a tab switch
  try {
    const s = await j("/api/summary");
    const sum = s.summary || s;
    $("cards").innerHTML = [
      ["nodes", sum.alive_nodes], ["actors", sum.actors],
      ["workers", sum.workers], ["queued", sum.queued_tasks],
      ["objects", sum.num_objects],
      ["store", ((sum.used||0)/1048576).toFixed(1)+" / "+
                ((sum.capacity||0)/1048576).toFixed(0)+" MB"],
      ["spilled", ((sum.spilled_bytes||0)/1048576).toFixed(1)+" MB"],
    ].map(([k,v]) =>
      `<div class="card"><div class="v">${esc(v ?? 0)}</div>
       <div class="k">${esc(k)}</div></div>`).join("");
    const data = await j("/api/" + tab);
    if (tab !== active) return;
    if (tab === "memory") {
      // Nested payload (lists inside a dict): one table per section,
      // not the flat spread — spreading an array makes index columns.
      const v = data.verdict || {};
      const problems = ["near_capacity","leak_suspects","spill_thrash"]
        .flatMap(k => (v[k]||[]).map(p => ({kind:k, ...p})));
      $("view").innerHTML =
        "<h3>totals</h3>" + table(
          {...(data.totals||{}), ...(data.disabled?{disabled:true}:{})}) +
        "<h3>jobs</h3>" + table(
          Object.entries(data.jobs||{}).map(([k,r]) => ({job:k, ...r}))) +
        "<h3>owners</h3>" + table(data.owners||[]) +
        "<h3>nodes</h3>" + table((data.nodes||[]).map(n => ({
          node:n.node, arena_used:n.arena_used,
          arena_capacity:n.arena_capacity, objects:n.tracked_objects,
          attributed:n.attributed_bytes, spilled:n.spilled_bytes}))) +
        "<h3>top objects</h3>" + table(data.top_objects||[]) +
        "<h3>verdict</h3>" + table(problems);
    } else if (tab === "transfers") {
      // Same nested-payload shape as memory: section tables, not a
      // flat spread.
      $("view").innerHTML =
        (data.disabled ? "<p><i>transfer instrumentation disabled " +
          "(transfer_report_interval_s or memory_report_interval_s " +
          "&le; 0)</i></p>" : "") +
        "<h3>flows (src &rarr; dst)</h3>" + table(data.flows||[]) +
        "<h3>provenance by job</h3>" + table(
          Object.entries(data.provenance||{}).map(([k,r]) =>
            ({job:k, ...r}))) +
        "<h3>locality</h3>" + table(
          Object.entries(data.locality||{}).map(([k,r]) =>
            ({job:k, ...r}))) +
        "<h3>top remote-pulling task classes</h3>" +
          table(data.tasks||[]) +
        "<h3>spill/restore ops by job</h3>" + table(
          [...new Set([...Object.keys(data.job_spill_ops||{}),
                       ...Object.keys(data.job_restore_ops||{})])]
            .map(k => ({job:k, spills:(data.job_spill_ops||{})[k]||0,
                        restores:(data.job_restore_ops||{})[k]||0})));
    } else $("view").innerHTML = table(
      tab === "resources" || tab === "metrics" || tab === "steps" ||
      tab === "serve" || tab === "compile"
        ? Object.entries(data).map(([k,v]) => ({name:k, ...(
            typeof v === "object" ? v : {value:v})}))
        : data);
    $("err").textContent = "";
  } catch (e) { $("err").textContent = "fetch failed: " + e; }
}
$("addr").textContent = location.host;
tabs(); tick(); setInterval(tick, 2000);
</script></body></html>"""


_UNKNOWN_API = object()


class Dashboard:
    def __init__(self, port: int = 8265):
        from .util import state as state_api

        self._state = state_api
        # Per-INSTANCE doctor cache: a class-level one would survive
        # shutdown/re-init and serve cluster A's verdict as cluster
        # B's health for up to a TTL.
        self._doctor_cache = (0.0, None)
        self._doctor_lock = threading.Lock()
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_GET(self):
                try:
                    status, payload, ctype = dashboard._route(self.path)
                except Exception as e:  # noqa: BLE001 — 500 surface
                    status = 500
                    payload = json.dumps({"error": repr(e)}).encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def _collect(self, kind: str):
        import ray_tpu

        state = self._state
        handlers = {
            "summary": lambda: ray_tpu.state_summary(),
            "nodes": state.list_nodes,
            "actors": state.list_actors,
            "tasks": state.list_tasks,
            "objects": state.list_objects,
            "placement_groups": state.list_placement_groups,
            "resources": lambda: {
                "total": ray_tpu.cluster_resources(),
                "available": ray_tpu.available_resources(),
            },
            "metrics": self._metrics,
            "memory": self._memory,
            "transfers": self._transfers,
            "serve": self._serve,
            "spans": self._spans,
            "steps": self._steps,
            "compile": self._compile,
            "doctor": self._doctor,
        }
        fn = handlers.get(kind)
        if fn is None:
            # Sentinel, not an exception: a KeyError raised INSIDE a
            # handler must stay a 500, not read as "no such api".
            return _UNKNOWN_API
        return fn()

    @staticmethod
    def _metrics():
        from .util.metrics import metrics_summary

        return metrics_summary()

    @staticmethod
    def _memory():
        """/api/memory — the cluster memory ledger: per-job/-owner
        attribution, top objects, per-node reports, verdict.memory
        (see `ray_tpu memory`)."""
        from .util.state import memory_summary

        return memory_summary()

    @staticmethod
    def _transfers():
        """/api/transfers — the cluster transfer matrix: per-(job,
        src_node, dst_node) flows with bytes/pulls/restores/aborts,
        per-job get provenance and locality hit rates, and the top
        remote-pulling task classes (see
        `ray_tpu memory --transfers`)."""
        from .util.state import transfer_summary

        return transfer_summary()

    @staticmethod
    def _serve():
        """Per-deployment serving observability: replica/ingress
        state from the controller merged with the head's request-path
        histograms (p50/p99, counts, in-flight, queue depth). Empty
        when serve was never started — the dashboard must work on
        training-only clusters."""
        from .serve.api import status_detail

        return status_detail()

    @staticmethod
    def _timeseries(query: str):
        """/api/timeseries?name=...&since=...&limit=... — the head's
        bounded snapshot ring (see util.metrics.metrics_timeseries)."""
        from urllib.parse import parse_qs

        from .util.metrics import metrics_timeseries

        params = {
            k: v[0] for k, v in parse_qs(query or "").items()
        }
        return metrics_timeseries(
            name=params.get("name"),
            since=float(params.get("since", 0.0) or 0.0),
            limit=int(params.get("limit", 0) or 0),
        )

    @staticmethod
    def _spans():
        """Most recent tracing spans, newest first (full OTLP export
        via util.tracing.export_otlp)."""
        from ._private.worker import global_worker

        worker = global_worker()
        if worker is None:
            return []
        records = worker.call("list_spans", limit=200)["spans"]
        return [
            {
                "name": r["name"],
                "trace": r["trace_id"][:8],
                "span": r["span_id"][:8],
                "parent": (r.get("parent_span_id") or "")[:8],
                "ms": round((r["end_ns"] - r["start_ns"]) / 1e6, 2),
                "attributes": r.get("attributes") or {},
            }
            for r in reversed(records)
        ]

    @staticmethod
    def _steps():
        """Gang-step telemetry digest: per-worker step-time stats +
        per-step skew, newest-first raw records behind it."""
        from ._private.worker import global_worker

        worker = global_worker()
        if worker is None:
            return {}
        reply = worker.call("step_summary", limit=1000)
        summary = reply["summary"]
        return {
            "max_skew_ms": summary.get("max_skew_ms", 0.0),
            "steps_observed": summary.get("steps_observed", 0),
            # Per-job goodput classification (productive vs
            # data_wait/h2d/ckpt_block/idle) over the same window.
            **{
                f"goodput {job or 'job'}": row
                for job, row in sorted(
                    summary.get("goodput", {}).items()
                )
            },
            **{
                f"rank {rank}": row
                for rank, row in sorted(
                    summary.get("workers", {}).items()
                )
            },
        }

    @staticmethod
    def _compile():
        """/api/compile — the head's XLA compile-watch table: one row
        per registered program (compile count, total ms, distinct
        shape digests) plus the current recompile-storm findings."""
        from ._private.worker import global_worker

        worker = global_worker()
        if worker is None:
            return {}
        summary = worker.call("compile_summary")["compile"]
        out = {
            name: {
                "compiles": row.get("compiles", 0),
                "total_ms": row.get("total_ms", 0.0),
                "distinct_shapes": row.get("distinct_shapes", 0),
            }
            for name, row in sorted(
                summary.get("programs", {}).items()
            )
        }
        for i, storm in enumerate(summary.get("storms", [])):
            out[f"storm {i}"] = {
                "program": storm.get("program"),
                "detail": storm.get("detail"),
            }
        return out

    #: Seconds a doctor verdict is served to polls before refresh:
    #: diagnose fans out per-worker inspect RPCs cluster-wide, far
    #: too heavy for the page's 2-second tick.
    _DOCTOR_TTL_S = 10.0

    def _doctor(self):
        """Stall-doctor verdict (rt.diagnose), cached for
        _DOCTOR_TTL_S. Stacks are skipped: a dashboard poll must not
        trigger cluster-wide profile captures — use `ray_tpu doctor`
        for those. The lock keeps one diagnose in flight no matter
        how many polls stack up behind a slow one (ThreadingHTTPServer
        + a 2 s page tick would otherwise fan out a cluster-wide
        diagnose per poll exactly when the cluster is sick); waiters
        re-check the cache the refresher just filled."""
        import time

        import ray_tpu

        with self._doctor_lock:
            now = time.monotonic()
            cached_at, verdict = self._doctor_cache
            if (
                verdict is None
                or now - cached_at >= self._DOCTOR_TTL_S
            ):
                verdict = ray_tpu.diagnose(capture_stacks=False)
                self._doctor_cache = (time.monotonic(), verdict)
        return verdict

    @staticmethod
    def _profile(query: str):
        """On-demand worker profiling (reference: dashboard reporter
        profile endpoints). /api/profile?pid=N[&kind=cpu|stack|memory]
        [&duration_s=S][&hz=H][&top=K][&node=<node hex>]."""
        from urllib.parse import parse_qs

        from .util.state import profile_worker

        params = {
            k: v[0] for k, v in parse_qs(query or "").items()
        }
        if "pid" not in params:
            raise ValueError("profile requires ?pid=<worker pid>")
        return profile_worker(
            int(params["pid"]),
            kind=params.get("kind", "cpu"),
            duration_s=float(params.get("duration_s", 5.0)),
            hz=float(params.get("hz", 100.0)),
            top=int(params.get("top", 20)),
            node_id=params.get("node"),
        )

    def _route(self, path: str):
        if path.startswith("/api/profile"):
            _, _, query = path.partition("?")
            payload = json.dumps(
                self._profile(query), default=str
            ).encode()
            return 200, payload, "application/json"
        if path.startswith("/api/timeseries"):
            _, _, query = path.partition("?")
            payload = json.dumps(
                self._timeseries(query), default=str
            ).encode()
            return 200, payload, "application/json"
        if path.startswith("/api/"):
            kind = path[len("/api/") :].strip("/")
            data = self._collect(kind)
            if data is _UNKNOWN_API:
                return (
                    404,
                    json.dumps({"error": f"no such api: {kind}"}).encode(),
                    "application/json",
                )
            return (
                200,
                json.dumps(data, default=str).encode(),
                "application/json",
            )
        if path == "/metrics":
            from .util.prometheus import render_prometheus

            return (
                200,
                render_prometheus(self._metrics()).encode(),
                "text/plain; version=0.0.4",
            )
        if path in ("/", "/index.html"):
            return 200, _PAGE.encode(), "text/html"
        return (
            404,
            json.dumps({"error": "not found"}).encode(),
            "application/json",
        )

    def stop(self) -> None:
        self._server.shutdown()


def start_dashboard(port: int = 8265) -> Dashboard:
    """Serve the dashboard from this (driver) process."""
    return Dashboard(port)
