"""Multi-node-in-one-box test cluster.

The reference's keystone fixture boots multiple raylets against one GCS
inside a single machine (reference: python/ray/cluster_utils.py —
Cluster:135, add_node:202; fixture ray_start_cluster,
python/ray/tests/conftest.py:508). Here each `add_node` starts a full
`NodeDaemon` (worker-node role) in-process with its own Unix socket,
shared-memory store and worker-process pool, registered against the
head daemon — so scheduling policies, cross-node object transfer and
fault-tolerance paths run hermetically on one machine.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Dict, Optional

from ._private.config import Config
from ._private.daemon import NodeDaemon


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_resources: Optional[Dict[str, float]] = None,
        system_config: Optional[dict] = None,
        use_tcp: bool = False,
    ):
        """`use_tcp=True` forces every daemon onto TCP loopback — the
        cross-host transport — so tests exercise the DCN wire format
        instead of Unix sockets (reference analogy: raylets always talk
        gRPC even in Cluster tests)."""
        self.session_dir = tempfile.mkdtemp(prefix="rt_cluster_")
        self.config = Config.from_env(system_config)
        self.use_tcp = use_tcp
        self.head: Optional[NodeDaemon] = None
        self.nodes: list[NodeDaemon] = []
        self._node_seq = 0
        if initialize_head:
            resources = dict(head_resources or {"CPU": 2.0})
            resources.setdefault("memory", float(2**32))
            self.head = NodeDaemon(
                os.path.join(self.session_dir, "head"),
                resources,
                self.config,
                is_head=True,
                listen_host="127.0.0.1" if use_tcp else None,
            )
            self.head.start()

    @property
    def address(self) -> str:
        assert self.head is not None
        return self.head.address

    def add_node(
        self,
        num_cpus: float = 2.0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> NodeDaemon:
        """Start a worker-node daemon registered with the head."""
        assert self.head is not None, "cluster has no head"
        self._node_seq += 1
        total = dict(resources or {})
        total.setdefault("CPU", float(num_cpus))
        total.setdefault("memory", float(2**32))
        node = NodeDaemon(
            os.path.join(self.session_dir, f"node-{self._node_seq}"),
            total,
            self.config,
            is_head=False,
            head_address=self.address,
            labels=labels,
            listen_host="127.0.0.1" if self.use_tcp else None,
        )
        node.start()
        self.nodes.append(node)
        return node

    def remove_node(self, node: NodeDaemon) -> None:
        """Tear a node down abruptly — the head observes the connection
        drop and runs its death path (reference: node death broadcast,
        test fixture Cluster.remove_node)."""
        if node in self.nodes:
            self.nodes.remove(node)
        node.shutdown()

    def wait_for_nodes(self, count: int, timeout: float = 10.0) -> None:
        """Block until the head sees `count` alive nodes (incl. head)."""
        assert self.head is not None
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self.head.control.alive_nodes()) >= count:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster did not reach {count} nodes within {timeout}s"
        )

    def crash_head(self) -> "NodeDaemon":
        """Take the head down abruptly (its control-plane state
        survives only via the gcs op log in the session dir). Worker
        nodes keep running; their heartbeat loops will resync once a
        head is restarted at the same address."""
        assert self.head is not None
        head, self.head = self.head, None
        self._head_resources = dict(head.resources)
        self._head_address = head.address
        head.shutdown()
        return head

    def restart_head(self) -> "NodeDaemon":
        """Start a fresh head over the SAME session dir (replays the
        gcs op log) and, for TCP clusters, the same port so surviving
        nodes and drivers can re-reach it."""
        assert self.head is None, "head still running"
        listen_port = 0
        if self.use_tcp and self._head_address.startswith("tcp://"):
            listen_port = int(self._head_address.rsplit(":", 1)[1])
        self.head = NodeDaemon(
            os.path.join(self.session_dir, "head"),
            self._head_resources,
            self.config,
            is_head=True,
            listen_host="127.0.0.1" if self.use_tcp else None,
            listen_port=listen_port,
        )
        self.head.start()
        return self.head

    def shutdown(self) -> None:
        # Phase 1: SIGKILL every daemon's worker tree up front, no
        # waits. With thousands of live workers on a small host the
        # graceful per-daemon path can take longer than the processes
        # deserve — and if anything earlier in teardown wedges, the
        # orphaned tree pins the pid table (observed: a 7k-worker
        # bench run saturating pid_max for good).
        for node in [*self.nodes, self.head]:
            if node is None:
                continue
            try:
                node.kill_worker_tree()
            except Exception:
                pass
        for node in self.nodes:
            try:
                node.shutdown()
            except Exception:
                pass
        self.nodes.clear()
        if self.head is not None:
            self.head.shutdown()
            self.head = None
