"""User-facing step-telemetry surface.

The runtime core lives in `_private/step_telemetry.py` (importable
from the data layer without pulling in jax); this module re-exports it
for train-loop authors and adds the head-side queries:

    report_step(step, rank=..., step_ms=...)  # hand-rolled loops
    step_summary()  # gang-step skew + per-worker stats
    step_records()  # raw per-step, per-rank phase records

Sessions created by the trainer emit records automatically on every
`train.report()` — these APIs are for loops that bypass the session
and for reading the head's aggregation back.
"""

from __future__ import annotations

from typing import List

from .._private.step_telemetry import (  # noqa: F401 — re-exports
    add_phase,
    phase_timer,
    report_step,
    steps_to_chrome_trace,
    take_phases,
    timed_iter,
)

__all__ = [
    "add_phase",
    "take_phases",
    "phase_timer",
    "timed_iter",
    "report_step",
    "steps_to_chrome_trace",
    "step_summary",
    "step_records",
]


def _worker():
    from .. import exceptions as exc
    from .._private.worker import global_worker

    worker = global_worker()
    if worker is None:
        raise exc.RayTpuError("ray_tpu.init() has not been called")
    return worker


def _flush_local() -> None:
    # Best-effort pre-read flush so records emitted this instant are
    # visible; a transient delivery failure requeues the batch for
    # the background flusher instead of failing the read.
    from ..util.metrics import flush_best_effort

    flush_best_effort()


def step_summary(limit: int = 1000) -> dict:
    """Head-side digest of the step telemetry: per-worker step-time
    stats and per-step gang skew (max - min step_ms across workers of
    the same step index)."""
    _flush_local()
    return _worker().call("step_summary", limit=limit)["summary"]


def step_records(limit: int = 1000) -> List[dict]:
    """Raw per-step, per-rank phase records from the head's ring."""
    _flush_local()
    return _worker().call("step_summary", limit=limit, records=True)[
        "records"
    ]
