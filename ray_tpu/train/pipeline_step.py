"""Pipeline-parallel training step for the flagship model.

Runs the FULL Llama training step with its layer stack partitioned
over the `pp` mesh axis (GPipe schedule inside one SPMD program,
parallel/pipeline.py), composing with sequence parallelism (ring
attention over `sp`) and expert parallelism (MoE all_to_all over `ep`)
in the same shard_map. The reference's pipeline story is runtime
channels between actor stages (reference: dag/compiled_dag_node.py:691
+ NCCL channels); here stage hops are `lax.ppermute` over ICI and the
optimizer update runs outside the shard_map under GSPMD, sharded
exactly like the parameters.

Mesh contract: axes ("pp", "sp", "ep"), any of them size 1. The batch
dim shards over `ep` (which doubles as the data axis — experts are
sharded over the same devices that hold different batch shards, the
standard DeepSeek/GShard layout), the sequence dim over `sp`, and the
layer stack over `pp`.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import (
    LlamaConfig,
    _layer,
    embed_tokens,
    masked_xent,
    model_norm,
    param_annotations,
)
from ..ops.norms import rotary_embedding
from ..parallel.pipeline import broadcast_from_last_stage, spmd_pipeline
from ..parallel.sharding import Annotated, checked_shard_map
from .train_step import TrainState, infer_opt_shardings


def _promote(x, axes):
    """Mark x varying over `axes` (no-op per axis when already so, or
    on a jax predating pcast) — required before psum/pmean under
    jax >= 0.7's varying-manual-axes check."""
    from ..parallel.collective import pcast_varying

    return pcast_varying(x, axes)


def to_pipeline_params(params: Any, pp: int) -> Any:
    """Reshape the stacked layer tree [L, ...] -> [pp, L/pp, ...] so
    the leading stage axis shards over `pp`."""
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda a: a.reshape(pp, a.shape[0] // pp, *a.shape[1:]),
        params["layers"],
    )
    return out


def _pipeline_param_specs(cfg: LlamaConfig) -> Any:
    """PartitionSpecs for to_pipeline_params' tree: stage axis on pp,
    expert axis on ep, everything else replicated (embed/lm_head are
    small at flagship scale relative to the layer stack; tp composes
    later if needed)."""
    ann = param_annotations(cfg)

    def layer_spec(a: Annotated) -> P:
        parts = ["pp", None]  # [stage, layers/stage, ...]
        for name in a.logical_axes[1:]:
            parts.append("ep" if name == "expert" else None)
        return P(*parts)

    return {
        "embed": P(),
        "layers": jax.tree.map(
            layer_spec, ann["layers"],
            is_leaf=lambda x: isinstance(x, Annotated),
        ),
        "final_norm": P(),
        "lm_head": P(),
    }


def make_pp_train_step(
    cfg: LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    *,
    num_microbatches: Optional[int] = None,
    donate: bool = True,
) -> Tuple[Callable, Callable]:
    """Build (init_fn, step_fn) for pipeline-parallel training.

    init_fn(key, init_params_fn) -> sharded TrainState (layer stack
    pre-reshaped to [pp, L/pp, ...]).
    step_fn(state, tokens, targets) -> (state, metrics); tokens are the
    GLOBAL batch [B, T] with B % (ep * num_microbatches) == 0 and
    T % sp == 0.
    """
    pp = mesh.shape["pp"]
    sp = mesh.shape.get("sp", 1)
    ep = mesh.shape.get("ep", 1)
    if cfg.n_layers % pp != 0:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={pp}"
        )
    num_mb = num_microbatches or max(2 * pp, 2)
    sp_axis = "sp" if sp > 1 else None
    ep_axis = "ep" if ep > 1 else None

    param_specs = _pipeline_param_specs(cfg)
    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    batch_spec = P("ep", "sp")  # [batch, seq]
    batch_sharding = NamedSharding(mesh, batch_spec)
    repl = NamedSharding(mesh, P())

    def pp_loss(params, tokens, targets):
        # Local shapes: tokens [b_loc, t_loc]; b_loc = B/ep, t_loc = T/sp.
        b_loc, t_loc = tokens.shape
        mb = b_loc // num_mb
        # Global positions of this rank's sequence shard drive RoPE and
        # ring attention's causal masking.
        sp_rank = lax.axis_index("sp") if sp > 1 else 0
        positions = sp_rank * t_loc + jnp.arange(t_loc)
        cos, sin = rotary_embedding(
            jnp.broadcast_to(positions, (mb, t_loc)),
            cfg.head_dim, cfg.rope_theta,
            getattr(cfg, "rope_scaling", None),
        )

        # Embedding runs on every pp rank (cheap vs the stack); only
        # rank 0's result is injected into the pipeline. Shared helper
        # so family conventions (Gemma sqrt(dim) scale) apply here too.
        x = embed_tokens(cfg, params, tokens)
        microbatches = x.reshape(num_mb, mb, t_loc, -1)
        stage_layers = jax.tree.map(lambda a: a[0], params["layers"])

        def stage_fn(layers, h):
            def body(xc, layer):
                return _layer(cfg, xc, layer, cos, sin, sp_axis, ep_axis)

            if cfg.remat:
                if cfg.remat_policy == "dots":
                    body = jax.checkpoint(
                        body,
                        policy=jax.checkpoint_policies
                        .dots_with_no_batch_dims_saveable,
                    )
                else:
                    body = jax.checkpoint(body)
            h, auxs = lax.scan(body, h, layers)
            # The pipeline carry is a single activation array, so the
            # MoE aux loss rides spmd_pipeline's rank-local accumulator
            # instead; it must vary over at most pp — average the data
            # axes here.
            aux = _promote(jnp.sum(auxs), ("sp", "ep"))
            return h, lax.pmean(aux, ("sp", "ep"))

        outs, aux_local = spmd_pipeline(
            stage_fn, stage_layers, microbatches,
            axis_name="pp", stacked_params=False, with_aux=True,
        )
        # Stage ranks each accumulated their own layers' aux over all
        # microbatches: sum stages, average microbatches to match the
        # non-pp loss_fn scale.
        aux = lax.psum(aux_local, "pp") / num_mb
        outs = broadcast_from_last_stage(outs, "pp")
        h = outs.reshape(b_loc, t_loc, -1)
        h = model_norm(cfg, h, params["final_norm"])
        logits = (h @ params["lm_head"]).astype(jnp.float32)

        nll_sum, count = masked_xent(logits, targets)
        # Reduce over BOTH data axes unconditionally (even size-1 axes
        # carry a formal varying mark from the batch in_spec, and
        # out_specs=P() demands a fully unvarying scalar).
        local = _promote(jnp.stack([nll_sum, count]), ("sp", "ep"))
        local = lax.psum(local, ("sp", "ep"))
        xent = local[0] / jnp.maximum(local[1], 1.0)
        return xent + cfg.moe_aux_weight * aux

    smapped = checked_shard_map(
        pp_loss,
        mesh,
        (param_specs, batch_spec, batch_spec),
        P(),
    )

    def init_fn(key, init_params_fn) -> TrainState:
        from .._private import compile_watch

        def build(k):
            return to_pipeline_params(init_params_fn(k), pp)

        params = compile_watch.instrument(
            "train.pipeline.init_params",
            jax.jit(build, out_shardings=param_shardings),
        )(key)
        opt_shardings = infer_opt_shardings(
            optimizer, params, param_shardings, repl
        )
        opt_state = compile_watch.instrument(
            "train.pipeline.init_opt_state",
            jax.jit(optimizer.init, out_shardings=opt_shardings),
        )(params)
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            opt_state=opt_state,
        )

    def _step(state: TrainState, tokens, targets):
        loss, grads = jax.value_and_grad(smapped)(
            state.params, tokens, targets
        )
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
        return (
            TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            ),
            metrics,
        )

    # Same compile-watch contract as make_train_step's "train.step":
    # one compile per geometry, recompile storms convicted by name.
    from .._private import compile_watch

    step_fn = compile_watch.instrument(
        "train.pp_step",
        jax.jit(
            _step,
            in_shardings=(None, batch_sharding, batch_sharding),
            out_shardings=(None, repl),
            donate_argnums=(0,) if donate else (),
        ),
    )
    return init_fn, step_fn
