"""Training library: JaxTrainer (DataParallelTrainer-shaped), sharded
train steps, sessions, backends, and checkpointing."""

from . import telemetry
from .backend import Backend, CpuTestBackend, JaxBackend
from .checkpoint import (
    CheckpointManager,
    load_metadata,
    pending_checkpoints,
    restore_checkpoint,
    save_checkpoint,
    wait_for_checkpoints,
)
from .config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from .session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    get_device_batches,
    report,
)
from .train_step import (
    TrainState,
    default_optimizer,
    make_train_step,
    prefetch_to_device,
    shard_batch,
)
from .mpmd_pipeline import MPMDPipeline, MPMDPipelineError
from .trainer import JaxTrainer
from .worker_group import WorkerGroup

__all__ = [
    "telemetry",
    "JaxTrainer",
    "ScalingConfig",
    "RunConfig",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "Backend",
    "JaxBackend",
    "CpuTestBackend",
    "WorkerGroup",
    "MPMDPipeline",
    "MPMDPipelineError",
    "TrainState",
    "make_train_step",
    "default_optimizer",
    "shard_batch",
    "prefetch_to_device",
    "report",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
    "get_device_batches",
    "save_checkpoint",
    "restore_checkpoint",
    "load_metadata",
    "wait_for_checkpoints",
    "pending_checkpoints",
    "CheckpointManager",
]
