"""Training library: JaxTrainer (DataParallelTrainer-shaped), sharded
train steps, sessions, backends, and checkpointing."""

from .backend import Backend, CpuTestBackend, JaxBackend
from .checkpoint import (
    CheckpointManager,
    load_metadata,
    restore_checkpoint,
    save_checkpoint,
)
from .config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from .session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from .train_step import (
    TrainState,
    default_optimizer,
    make_train_step,
    shard_batch,
)
from .trainer import JaxTrainer
from .worker_group import WorkerGroup

__all__ = [
    "JaxTrainer",
    "ScalingConfig",
    "RunConfig",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "Backend",
    "JaxBackend",
    "CpuTestBackend",
    "WorkerGroup",
    "TrainState",
    "make_train_step",
    "default_optimizer",
    "shard_batch",
    "report",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
    "save_checkpoint",
    "restore_checkpoint",
    "load_metadata",
    "CheckpointManager",
]
