"""Shared training configs (reference: python/ray/air/config.py —
ScalingConfig / RunConfig / CheckpointConfig / FailureConfig dataclasses
consumed by Trainer.fit)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from ..parallel.mesh import MeshSpec


@dataclasses.dataclass
class ScalingConfig:
    """How to scale training (reference: air/config.py ScalingConfig).

    TPU-native twist: instead of `num_workers × use_gpu`, the unit of
    scale is a device mesh. `num_workers` is the number of host
    processes in the gang (1 = single-controller); `mesh` is the
    per-gang parallelism layout; `resources_per_worker` feeds the
    placement-group request when the gang is scheduled on a cluster.
    """

    num_workers: int = 1
    use_tpu: bool = True
    mesh: Optional[MeshSpec] = None
    resources_per_worker: Optional[Dict[str, float]] = None

    def resolved_mesh(self) -> MeshSpec:
        return self.mesh if self.mesh is not None else MeshSpec.auto()


@dataclasses.dataclass
class CheckpointConfig:
    """(reference: air/config.py CheckpointConfig — top-k retention)."""

    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0  # steps; 0 = only on report()


@dataclasses.dataclass
class FailureConfig:
    """(reference: air/config.py FailureConfig.max_failures)."""

    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    """(reference: air/config.py RunConfig — name + storage + FT)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig
    )
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig
    )


@dataclasses.dataclass
class Result:
    """What Trainer.fit returns (reference: air/result.py)."""

    metrics: Dict[str, Any]
    checkpoint_path: Optional[str]
    error: Optional[BaseException] = None
    metrics_history: list = dataclasses.field(default_factory=list)
