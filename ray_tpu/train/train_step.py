"""Sharded training-step construction.

The TPU-native replacement for the reference's DDP wrapping
(reference: train/torch/train_loop_utils.py:162 prepare_model wraps in
DistributedDataParallel; config.py:115 inits the NCCL group): here
parameters/optimizer state are laid out on the mesh via logical-axis
rules and the step is one `jax.jit` whose gradient/psum collectives
XLA inserts from the shardings (GSPMD). dp+fsdp+tp+sp all come from
the same code path — the MeshSpec decides which are active.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import (
    ACT_RULES,
    PARAM_RULES,
    Rules,
    spec_for,
    tree_shardings,
)


@dataclasses.dataclass
class TrainState:
    """Param + optimizer-state pytree (registered below)."""

    step: jax.Array
    params: Any
    opt_state: Any


jax.tree_util.register_dataclass(
    TrainState, data_fields=["step", "params", "opt_state"], meta_fields=[]
)


def default_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    warmup_steps: int = 100,
    total_steps: int = 10000,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
) -> optax.GradientTransformation:
    """AdamW + cosine schedule + global-norm clipping — the standard
    pretraining recipe (reference parity: the configs its release
    train_tests use for Llama-2 pretraining)."""
    warmup_steps = min(warmup_steps, max(1, total_steps // 10))
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=learning_rate,
        warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1),
        end_value=learning_rate * 0.1,
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def _path_keys(path) -> Tuple[str, ...]:
    """Dict/attribute names along a pytree key path (indices dropped)."""
    keys = []
    for entry in path:
        name = getattr(entry, "key", None) or getattr(entry, "name", None)
        if isinstance(name, str):
            keys.append(name)
    return tuple(keys)


def infer_opt_shardings(
    optimizer: optax.GradientTransformation,
    params: Any,
    param_shardings: Any,
    replicated: NamedSharding,
) -> Any:
    """Sharding tree for optimizer.init's output: each moment leaf
    (e.g. adam mu/nu at path (..., 'mu', <param path>)) inherits the
    sharding of the parameter whose key-path is a suffix of its own;
    everything else (step counters) is replicated."""
    by_path: Dict[Tuple[str, ...], Any] = {}
    for path, sharding in jax.tree_util.tree_flatten_with_path(
        param_shardings
    )[0]:
        by_path[_path_keys(path)] = sharding
    abstract = jax.eval_shape(optimizer.init, params)

    def leaf_sharding(path, leaf):
        keys = _path_keys(path)
        for start in range(len(keys)):
            match = by_path.get(keys[start:])
            if match is not None:
                return match
        return replicated

    return jax.tree_util.tree_map_with_path(leaf_sharding, abstract)


def make_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_annotations: Any,
    *,
    param_rules: Rules = PARAM_RULES,
    batch_logical_axes: Tuple[Optional[str], ...] = ("batch", "seq"),
    act_rules: Rules = ACT_RULES,
    donate: bool = True,
):
    """Build (init_fn, step_fn).

    loss_fn(params, tokens, targets) -> scalar loss.
    init_fn(key, init_params_fn) -> sharded TrainState.
    step_fn(state, tokens, targets) -> (state, metrics) — jitted, with
    params/opt-state donated so the update is in-place in HBM.
    """
    param_shardings = tree_shardings(mesh, param_annotations, param_rules)
    batch_sharding = NamedSharding(
        mesh, spec_for(batch_logical_axes, act_rules)
    )
    repl = NamedSharding(mesh, P())

    def init_fn(key, init_params_fn) -> TrainState:
        from .._private import compile_watch

        # jit with out_shardings lays parameters out directly on the
        # mesh — no host-side full copy of the model is ever built.
        params = compile_watch.instrument(
            "train.init_params",
            jax.jit(init_params_fn, out_shardings=param_shardings),
        )(key)
        # Optimizer moments must shard exactly like their parameters
        # (the ZeRO-3 property); jit's inference doesn't guarantee it,
        # so derive explicit out_shardings by param-path matching.
        opt_shardings = infer_opt_shardings(
            optimizer, params, param_shardings, repl
        )
        opt_state = compile_watch.instrument(
            "train.init_opt_state",
            jax.jit(optimizer.init, out_shardings=opt_shardings),
        )(params)
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state
        )

    def _step(state: TrainState, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, tokens, targets
        )
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        metrics = {"loss": loss, "grad_norm": gnorm}
        return (
            TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            ),
            metrics,
        )

    # Registered with the XLA compile watcher by name: a training
    # loop's step must compile once per (state, batch) geometry and
    # never again — a drifting batch shape that re-traces it every
    # iteration now convicts itself in `doctor` verdict.compile
    # (recompile_storm) instead of reading as a mysteriously slow
    # loop, and the cold-compile step bills compile_ms as a stall.
    from .._private import compile_watch

    step_fn = compile_watch.instrument(
        "train.step",
        jax.jit(
            _step,
            in_shardings=(None, batch_sharding, batch_sharding),
            out_shardings=(None, repl),
            donate_argnums=(0,) if donate else (),
        ),
    )
    return init_fn, step_fn


def shard_batch(batch, mesh: Mesh, logical_axes=("batch", "seq"),
                rules: Rules = ACT_RULES):
    """Device-put host batches onto the mesh data axes."""
    sharding = NamedSharding(mesh, spec_for(logical_axes, rules))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def prefetch_to_device(
    batches,
    mesh: Mesh,
    *,
    buffer_size: int = 2,
    logical_axes: Tuple[Optional[str], ...] = ("batch", "seq"),
    rules: Rules = ACT_RULES,
):
    """Double-buffer host batches onto the mesh: batch N+1's
    device_put is dispatched before batch N is consumed, so its H2D
    transfer overlaps step N's compute (flax.jax_utils
    prefetch_to_device pattern; device_put is an async dispatch on
    TPU/GPU backends).

    `batches` is any iterator of pytrees (e.g. Dataset.iter_batches
    output); each leaf is device_put with the same sharding
    shard_batch would use. buffer_size=2 is classic double buffering;
    1 degenerates to put-then-yield with no overlap.
    """
    import time as _time
    from collections import deque

    from .._private import step_telemetry

    if buffer_size < 1:
        raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
    sharding = NamedSharding(mesh, spec_for(logical_axes, rules))

    def put(batch):
        # H2D dispatch time, attributed per step (device_put is an
        # async dispatch on TPU/GPU — what's measured is the stall the
        # loop pays, which is exactly the number the doctor wants).
        t0 = _time.monotonic()
        out = jax.tree.map(
            lambda x: jax.device_put(x, sharding), batch
        )
        step_telemetry.add_phase(
            "h2d_ms", (_time.monotonic() - t0) * 1e3
        )
        return out

    window: "deque" = deque()
    iterator = iter(batches)

    def pull():
        # data_wait is timed at this outermost consumer boundary;
        # phase_timer's reentrancy guard keeps a telemetry-wrapped
        # source — even one buried under user transforms, e.g.
        # (augment(b) for b in ds.iter_batches(...)) — from billing
        # the same stall twice.
        with step_telemetry.phase_timer("data_wait_ms"):
            return next(iterator)

    while True:
        while len(window) < buffer_size:
            try:
                window.append(put(pull()))
            except StopIteration:
                while window:
                    yield window.popleft()
                return
        yield window.popleft()
