"""Gang of training workers as actors on the distributed runtime.

Reference anatomy: BackendExecutor creates a placement group + a
WorkerGroup of RayTrainWorker actors, sets ranks, and launches the
train loop on each (reference: train/_internal/backend_executor.py:135,
219, 369, 451; worker_group.py:19). Here the gang members are actors of
our own runtime; rank/world-size context is installed per worker and
functions are executed on all members in parallel.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from .. import api as rt
from ..actor import ActorHandle


class _TrainWorker:
    """Actor body running on each gang member (reference:
    train/_internal/worker_group.py RayTrainWorker)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self._state = {}

    def run(self, fn, args=(), kwargs=None):
        return fn(*args, **(kwargs or {}))

    def run_with_context(
        self,
        fn,
        experiment_name="",
        args=(),
        trial_dir=None,
        dataset_shards=None,
    ):
        from .session import TrainContext, clear_session, init_session

        context = TrainContext(
            world_rank=self.rank,
            world_size=self.world_size,
            local_rank=self.rank,
            experiment_name=experiment_name,
            trial_dir=trial_dir,
        )
        session = init_session(context, dataset_shards=dataset_shards)
        try:
            result = fn(*args)
        finally:
            clear_session()
            # Fit-exit durability barrier, worker-side: this rank's
            # async checkpoint saves must persist before the gang
            # result (which names them) reaches the trainer.
            from .checkpoint import wait_for_checkpoints

            wait_for_checkpoints()
        return {
            "result": result,
            "reported": session.results,
            "checkpoint": session.latest_checkpoint,
        }


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Optional[dict] = None,
    ):
        self.size = num_workers
        options = dict(resources_per_worker or {})
        actor_cls = rt.remote(
            num_cpus=options.pop("CPU", 1),
            num_tpus=options.pop("TPU", 0),
            resources=options or None,
        )(_TrainWorker)
        self.workers: List[ActorHandle] = [
            actor_cls.remote(rank, num_workers)
            for rank in range(num_workers)
        ]

    def run_all(self, fn: Callable, args=(), kwargs=None) -> List[Any]:
        """Execute fn on every member; gather results (reference:
        backend_executor's start_training fan-out)."""
        refs = [
            w.run.remote(fn, args, kwargs or {}) for w in self.workers
        ]
        return rt.get(refs)

    def run_per_rank(
        self, fn: Callable, args_for_rank: Callable[[int], tuple]
    ) -> List[Any]:
        refs = [
            w.run.remote(fn, args_for_rank(rank))
            for rank, w in enumerate(self.workers)
        ]
        return rt.get(refs)

    def run_train_loop(
        self,
        fn: Callable,
        experiment_name="",
        args=(),
        trial_dir=None,
        dataset_shards_per_rank=None,
    ):
        refs = [
            w.run_with_context.remote(
                fn,
                experiment_name,
                args,
                trial_dir,
                dataset_shards_per_rank[rank]
                if dataset_shards_per_rank
                else None,
            )
            for rank, w in enumerate(self.workers)
        ]
        return rt.get(refs)

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                rt.kill(w)
            except Exception:
                pass
