"""JaxTrainer — the DataParallelTrainer-shaped entry point.

Reference call stack being mirrored (SURVEY.md §3.4): TorchTrainer.fit
→ DataParallelTrainer.training_loop → BackendExecutor.start (creates
the worker gang, sets ranks, runs backend hooks) → per-worker
train_loop_per_worker with a session for report()/checkpoints →
TrainingIterator gathers results; failures restart from the latest
checkpoint up to FailureConfig.max_failures (backend_executor.py:759).

TPU-native shape: `num_workers=1` is the single-controller JAX mode —
the loop runs in-process and pjit spans every device the process sees
(a whole slice on real pods). `num_workers>1` builds an actor gang via
WorkerGroup + JaxBackend rendezvous for multi-host DCN setups.
"""

from __future__ import annotations

import os
import tempfile
import traceback
from typing import Any, Callable, Dict, Optional

from .backend import Backend, JaxBackend
from .checkpoint import wait_for_checkpoints
from .config import Result, RunConfig, ScalingConfig
from .session import TrainContext, clear_session, init_session
from .worker_group import WorkerGroup


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable[[Optional[dict]], Any],
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend: Optional[Backend] = None,
        backend_config: Optional[dict] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend = backend or JaxBackend()
        self.backend_config = backend_config or {}
        self.datasets = datasets or {}

    # -- public API (reference: BaseTrainer.fit, base_trainer.py:567) --
    def fit(self) -> Result:
        max_failures = self.run_config.failure_config.max_failures
        name = self.run_config.name or "jax_trainer"
        # One storage dir for all attempts: retries find the previous
        # attempt's checkpoint marker there and resume from it.
        storage = self.run_config.storage_path or tempfile.mkdtemp(
            prefix=f"rt_train_{name}_"
        )
        os.makedirs(storage, exist_ok=True)
        attempt = 0
        while True:
            try:
                return self._fit_once(name, storage)
            except Exception as e:  # noqa: BLE001
                attempt += 1
                if attempt > max_failures:
                    return Result(
                        metrics={}, checkpoint_path=None, error=e
                    )
                traceback.print_exc()

    # ------------------------------------------------------------------
    def _fit_once(self, name: str, storage: str) -> Result:
        if self.scaling_config.num_workers <= 1:
            return self._fit_local(name, storage)
        return self._fit_gang(name, storage)

    def _loop_args(self):
        return (
            (self._train_loop_config,)
            if self._train_loop_config is not None
            or self._takes_config()
            else ()
        )

    def _takes_config(self) -> bool:
        import inspect

        try:
            sig = inspect.signature(self._train_loop)
            return len(sig.parameters) > 0
        except (TypeError, ValueError):
            return False

    def _make_shards(self, world_size: int, rank: int):
        """Streaming-split each dataset; rank's shard only (reference:
        DataConfig streaming-split, train/_internal/data_config.py:112)."""
        if not self.datasets:
            return {}
        if not hasattr(self, "_split_cache"):
            self._split_cache = {
                name: ds.streaming_split(world_size, equal=True)
                for name, ds in self.datasets.items()
            }
        return {
            name: splits[rank]
            for name, splits in self._split_cache.items()
        }

    def _make_gang_shards(self, world_size: int):
        if not self.datasets:
            return None
        return [
            self._make_shards(world_size, rank)
            for rank in range(world_size)
        ]

    def _fit_local(self, name: str, storage: str) -> Result:
        """Single-controller path: the loop runs here, pjit spans all
        visible devices."""
        history = []

        def on_result(metrics, checkpoint):
            history.append(dict(metrics))

        context = TrainContext(
            world_rank=0,
            world_size=1,
            experiment_name=name,
            trial_dir=storage,
        )
        session = init_session(
            context,
            result_callback=on_result,
            dataset_shards=self._make_shards(1, rank=0),
        )
        try:
            self._train_loop(*self._loop_args())
        finally:
            clear_session()
            # Fit-exit durability barrier: async saves issued by the
            # loop must be on disk before fit() returns (or before a
            # retry attempt restores from them).
            wait_for_checkpoints()
        metrics = history[-1] if history else {}
        return Result(
            metrics=metrics,
            checkpoint_path=session.latest_checkpoint,
            metrics_history=history,
        )

    def _fit_gang(self, name: str, storage: str) -> Result:
        """Multi-worker gang over the actor runtime (reference:
        BackendExecutor.start + start_training)."""
        group = WorkerGroup(
            self.scaling_config.num_workers,
            self.scaling_config.resources_per_worker,
        )
        try:
            self.backend.on_start(group, self.backend_config)
            outs = group.run_train_loop(
                self._train_loop,
                name,
                self._loop_args(),
                trial_dir=storage,
                dataset_shards_per_rank=self._make_gang_shards(
                    self.scaling_config.num_workers
                ),
            )
        finally:
            self.backend.on_shutdown(group)
            group.shutdown()
        rank0 = outs[0]
        history = rank0["reported"]
        return Result(
            metrics=history[-1] if history else {},
            checkpoint_path=rank0["checkpoint"],
            metrics_history=history,
        )
