"""Checkpoint save/restore with top-k retention and async persistence.

Orbax-backed sharded checkpointing (the TPU ecosystem standard),
wrapped in the reference's Checkpoint-directory semantics (reference:
train/_checkpoint.py Checkpoint = a directory handle;
train/_internal/checkpoint_manager.py top-k retention by score).

Async persistence (reference: orbax AsyncCheckpointer split — a
blocking device->host snapshot, then commit off the critical path):
``save_checkpoint(..., async_save=True)`` snapshots the pytree to host
memory synchronously (safe against donated buffers: the NEXT train
step may reuse the device HBM the moment save_checkpoint returns) and
hands the disk write to a single background writer thread, so step
N+1 runs while save N persists. ``wait_for_checkpoints()`` is the
durability barrier: the trainer calls it at fit-exit, and
restore/retention paths call it before trusting directory contents.
The writer publishes ``metadata.json`` only AFTER the array data is
fully written, so its presence marks a complete checkpoint.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from .._private import step_telemetry

# -- async writer machinery --------------------------------------------------

_PENDING_LOCK = threading.Lock()
#: path -> futures of the in-flight background writes for that path
#: (same-path re-saves append; the single writer runs them in order).
_PENDING: Dict[str, List[Future]] = {}
_EXECUTOR: Optional[ThreadPoolExecutor] = None


def _writer() -> ThreadPoolExecutor:
    """One writer thread: saves persist in submission order, and at
    most one disk commit competes with training for host resources."""
    global _EXECUTOR
    with _PENDING_LOCK:
        if _EXECUTOR is None:
            _EXECUTOR = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="rt-ckpt-writer"
            )
        return _EXECUTOR


def _host_snapshot(state: Any) -> Any:
    """Blocking device->host copy of a pytree. Must complete BEFORE the
    caller's next train step: with donate_argnums the step reuses the
    state's HBM in place, so a lazy read from the writer thread would
    see garbage. Non-jax pytrees (numpy/python) pass through."""
    try:
        import jax

        return jax.device_get(state)
    except ImportError:
        return state


def _fully_addressable(state: Any) -> bool:
    """True when every array in the pytree lives on devices this
    process can read. device_get raises on arrays spanning
    non-addressable devices (multi-host meshes), so async_save falls
    back to the sync orbax path — which gathers per-host — for such
    state."""
    try:
        import jax
    except ImportError:
        return True
    return all(
        getattr(leaf, "is_fully_addressable", True)
        for leaf in jax.tree.leaves(state)
    )


def _write_payload(path: str, state: Any, metadata: Optional[dict]) -> None:
    """Persist one checkpoint directory. metadata.json lands LAST so
    readers can treat its presence as the completeness marker."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "state"), state)
    ckptr.wait_until_finished()
    if metadata is not None:
        tmp = os.path.join(path, "metadata.json.tmp")
        with open(tmp, "w") as f:
            json.dump(metadata, f)
        os.replace(tmp, os.path.join(path, "metadata.json"))


def save_checkpoint(
    path: str,
    state: Any,
    metadata: Optional[dict] = None,
    *,
    async_save: bool = False,
) -> str:
    """Save a pytree (sharded arrays gathered per-host by orbax).

    async_save=True returns as soon as the state is snapshotted to
    host memory; the disk write runs on a background writer thread.
    Call :func:`wait_for_checkpoints` (the trainer does at fit-exit)
    before treating the directory as durable. State spanning
    non-addressable devices (multi-host meshes) cannot be host-
    snapshotted from one process, so it saves synchronously — orbax
    gathers per-host — rather than racing the next step's donation.
    """
    path = os.path.abspath(path)
    # The step-blocking portion of a save (full write when sync, the
    # device->host snapshot when async) is a train-loop phase the step
    # telemetry attributes per step.
    t0 = time.monotonic()
    if not async_save or not _fully_addressable(state):
        _write_payload(path, state, metadata)
        step_telemetry.add_phase(
            "ckpt_block_ms", (time.monotonic() - t0) * 1e3
        )
        return path
    snapshot = _host_snapshot(state)
    executor = _writer()
    with _PENDING_LOCK:
        # Submit under the lock: registration is atomic with the
        # submit, so a concurrent barrier can never miss an in-flight
        # write (and the single writer thread already serializes
        # same-path saves in submission order).
        future = executor.submit(_write_payload, path, snapshot, metadata)
        _PENDING.setdefault(path, []).append(future)
    step_telemetry.add_phase(
        "ckpt_block_ms", (time.monotonic() - t0) * 1e3
    )
    return path


def _wait_futures(path: str, futures: List[Future]) -> None:
    """Wait for the given writes; deregister them; re-raise the first
    error. Deregistration happens only AFTER the result — a concurrent
    barrier that snapshots _PENDING mid-wait still sees (and waits on)
    the in-flight write."""
    first_error: Optional[BaseException] = None
    for future in futures:
        try:
            future.result()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            if first_error is None:
                first_error = e
    with _PENDING_LOCK:
        remaining = _PENDING.get(path)
        if remaining is not None:
            remaining[:] = [f for f in remaining if f not in futures]
            if not remaining:
                del _PENDING[path]
    if first_error is not None:
        raise first_error


def wait_for_checkpoints(path: Optional[str] = None) -> None:
    """Durability barrier for async saves. With a path, waits only for
    that checkpoint; otherwise drains every pending save. Re-raises
    the first write error — a failed persist must surface at the
    barrier, not vanish into a daemon thread."""
    if path is not None:
        path = os.path.abspath(path)
        with _PENDING_LOCK:
            futures = list(_PENDING.get(path, ()))
        if futures:
            _wait_futures(path, futures)
        return
    first_error: Optional[BaseException] = None
    while True:
        with _PENDING_LOCK:
            items = [(p, list(fs)) for p, fs in _PENDING.items()]
        if not items:
            break
        for pending_path, futures in items:
            try:
                _wait_futures(pending_path, futures)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_error is None:
                    first_error = e
    if first_error is not None:
        raise first_error


def pending_checkpoints() -> List[str]:
    """Paths with an in-flight background write (newest last)."""
    with _PENDING_LOCK:
        return list(_PENDING)


def restore_checkpoint(path: str, target: Any) -> Any:
    """Restore into the sharding/structure of `target` (an abstract or
    concrete pytree). Waits for any in-flight save of `path` first so
    an async save followed by an immediate restore reads full data."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    wait_for_checkpoints(path)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.join(path, "state"), target)


def load_metadata(path: str) -> dict:
    wait_for_checkpoints(path)
    meta_path = os.path.join(path, "metadata.json")
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        return json.load(f)


class CheckpointManager:
    """Keep-last-k checkpoint retention (reference:
    train/_internal/checkpoint_manager.py; score-based top-k TBD)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None):
        self.root = os.path.abspath(root)
        self.num_to_keep = num_to_keep
        os.makedirs(self.root, exist_ok=True)
        self._checkpoints: List[Tuple[int, str]] = []

    def save(
        self,
        step: int,
        state: Any,
        metrics: Optional[dict] = None,
        *,
        async_save: bool = False,
    ):
        path = os.path.join(self.root, f"checkpoint_{step:08d}")
        save_checkpoint(
            path,
            state,
            {"step": step, **(metrics or {})},
            async_save=async_save,
        )
        self._checkpoints.append((step, path))
        if self.num_to_keep is not None:
            while len(self._checkpoints) > self.num_to_keep:
                _, old = self._checkpoints.pop(0)
                # Never delete a directory whose write is still in
                # flight — the writer would resurrect a half-deleted
                # tree and "retained" checkpoints could be corrupt.
                wait_for_checkpoints(old)
                shutil.rmtree(old, ignore_errors=True)
        return path

    def wait(self) -> None:
        """Block until every save issued through this manager (and any
        other async save in the process) is durable."""
        wait_for_checkpoints()

    def latest(self) -> Optional[str]:
        wait_for_checkpoints()
        existing = sorted(
            d
            for d in os.listdir(self.root)
            if d.startswith("checkpoint_")
        )
        if not existing:
            return None
        return os.path.join(self.root, existing[-1])
