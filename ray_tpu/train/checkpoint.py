"""Checkpoint save/restore with top-k retention.

Orbax-backed sharded checkpointing (the TPU ecosystem standard),
wrapped in the reference's Checkpoint-directory semantics (reference:
train/_checkpoint.py Checkpoint = a directory handle;
train/_internal/checkpoint_manager.py top-k retention by score)."""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple


def save_checkpoint(path: str, state: Any, metadata: Optional[dict] = None):
    """Save a pytree (sharded arrays gathered per-host by orbax)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "state"), state)
    ckptr.wait_until_finished()
    if metadata is not None:
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(metadata, f)


def restore_checkpoint(path: str, target: Any) -> Any:
    """Restore into the sharding/structure of `target` (an abstract or
    concrete pytree)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.join(path, "state"), target)


def load_metadata(path: str) -> dict:
    meta_path = os.path.join(path, "metadata.json")
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        return json.load(f)


class CheckpointManager:
    """Keep-last-k checkpoint retention (reference:
    train/_internal/checkpoint_manager.py; score-based top-k TBD)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None):
        self.root = os.path.abspath(root)
        self.num_to_keep = num_to_keep
        os.makedirs(self.root, exist_ok=True)
        self._checkpoints: List[Tuple[int, str]] = []

    def save(self, step: int, state: Any, metrics: Optional[dict] = None):
        path = os.path.join(self.root, f"checkpoint_{step:08d}")
        save_checkpoint(path, state, {"step": step, **(metrics or {})})
        self._checkpoints.append((step, path))
        if self.num_to_keep is not None:
            while len(self._checkpoints) > self.num_to_keep:
                _, old = self._checkpoints.pop(0)
                shutil.rmtree(old, ignore_errors=True)
        return path

    def latest(self) -> Optional[str]:
        existing = sorted(
            d
            for d in os.listdir(self.root)
            if d.startswith("checkpoint_")
        )
        if not existing:
            return None
        return os.path.join(self.root, existing[-1])
