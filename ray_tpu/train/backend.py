"""Training backends: per-gang accelerator setup hooks.

Reference anatomy (train/backend.py Backend.on_start/on_training_start/
on_shutdown; torch/config.py:66 _setup_torch_process_group;
torch/xla/config.py:120 proves accelerator-specific pod init belongs in
a Backend). The TPU/JAX backend's job is the multi-host rendezvous the
reference does with NCCL init: run `jax.distributed.initialize` on
every gang worker with the coordinator address, then build the mesh.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class Backend:
    def on_start(self, worker_group, backend_config: dict) -> None:
        """Called after the worker gang is up, before the train loop."""

    def on_shutdown(self, worker_group) -> None:
        """Called when training finishes."""


class JaxBackend(Backend):
    """Initializes JAX multi-host coordination across the gang
    (replaces torch dist.init_process_group(backend='nccl'),
    reference train/torch/config.py:115).

    Multi-slice: pass `backend_config={"slices": S}` for a gang that
    spans S TPU slices. All S*H processes join ONE jax.distributed
    world (one coordinator — on real multi-slice hardware the
    cross-slice transport is DCN, reached through the same runtime);
    each worker additionally learns its slice id (contiguous rank
    blocks) via RT_SLICE_ID for slice-aware application logic such as
    per-slice data loading. Mesh construction itself groups by the
    hardware's `slice_index` (or process boundaries on virtual test
    meshes — MeshSpec._build_hybrid). Train steps then shard their
    batch over the hybrid `dcn_dp` axis of `MeshSpec(dcn_dp=S, ...)`
    — the cross-slice traffic is exactly the per-step gradient
    all-reduce (SURVEY §5.8; reference analog: the multi-node NCCL
    world, train/torch/config.py:66-116).
    """

    def on_start(self, worker_group, backend_config: dict) -> None:
        coordinator = backend_config.get("coordinator_address")
        num_processes = worker_group.size
        slices = int(backend_config.get("slices", 1))
        if num_processes % max(slices, 1) != 0:
            raise ValueError(
                f"gang of {num_processes} workers not divisible by "
                f"slices={slices}"
            )
        if coordinator is None or num_processes <= 1:
            return

        def _init_jax_distributed(
            coordinator, num_processes, process_id, slice_id
        ):
            import os

            import jax

            os.environ["RT_SLICE_ID"] = str(slice_id)
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )

        per_slice = num_processes // max(slices, 1)
        worker_group.run_per_rank(
            _init_jax_distributed,
            lambda rank: (
                coordinator,
                num_processes,
                rank,
                rank // per_slice,
            ),
        )


class CpuTestBackend(Backend):
    """Forces workers onto the CPU backend with N virtual devices —
    the hermetic-test analog of a TPU slice (SURVEY.md §4 lesson)."""

    def on_start(self, worker_group, backend_config: dict) -> None:
        n = backend_config.get("virtual_devices", 8)

        def _force_cpu(n):
            import os

            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n}"
            ).strip()
            os.environ["JAX_PLATFORMS"] = "cpu"

        worker_group.run_per_rank(_force_cpu, lambda rank: (n,))
