"""Training backends: per-gang accelerator setup hooks.

Reference anatomy (train/backend.py Backend.on_start/on_training_start/
on_shutdown; torch/config.py:66 _setup_torch_process_group;
torch/xla/config.py:120 proves accelerator-specific pod init belongs in
a Backend). The TPU/JAX backend's job is the multi-host rendezvous the
reference does with NCCL init: run `jax.distributed.initialize` on
every gang worker with the coordinator address, then build the mesh.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class Backend:
    def on_start(self, worker_group, backend_config: dict) -> None:
        """Called after the worker gang is up, before the train loop."""

    def on_shutdown(self, worker_group) -> None:
        """Called when training finishes."""


class JaxBackend(Backend):
    """Initializes JAX multi-host coordination across the gang
    (replaces torch dist.init_process_group(backend='nccl'),
    reference train/torch/config.py:115).
    """

    def on_start(self, worker_group, backend_config: dict) -> None:
        coordinator = backend_config.get("coordinator_address")
        num_processes = worker_group.size
        if coordinator is None or num_processes <= 1:
            return

        def _init_jax_distributed(coordinator, num_processes, process_id):
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )

        worker_group.run_per_rank(
            _init_jax_distributed,
            lambda rank: (coordinator, num_processes, rank),
        )


class CpuTestBackend(Backend):
    """Forces workers onto the CPU backend with N virtual devices —
    the hermetic-test analog of a TPU slice (SURVEY.md §4 lesson)."""

    def on_start(self, worker_group, backend_config: dict) -> None:
        n = backend_config.get("virtual_devices", 8)

        def _force_cpu(n):
            import os

            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n}"
            ).strip()
            os.environ["JAX_PLATFORMS"] = "cpu"

        worker_group.run_per_rank(_force_cpu, lambda rank: (n,))
