"""MPMD pipeline-parallel training: 1F1B stage gangs over channels.

The second half of the pipeline story. `train/pipeline_step.py` runs
GPipe INSIDE one jitted SPMD program — every device executes every
schedule tick (invalid ticks masked, so the bubble is paid as real
FLOPs) and one giant program compiles for the whole stack. This
module is the MPMD mode the PAPERS.md "Scaling Deep Learning Training
with MPMD Pipeline Parallelism" paper argues for, built the way the
reference builds pipelines (compiled actor DAGs over channels,
dag/compiled_dag_node.py): the layer stack is partitioned into
chunks, each PHYSICAL stage is an actor running its OWN small jitted
fwd/bwd programs (compile time stays flat in stage size, not model
size), and activations/activation-gradients ride ahead-of-time wired
channel edges (`dag/edges.py`: shm same-host, TCP cross-host, bounded
capacity = backpressure) under a 1F1B schedule from
`parallel/schedule.py` — warmup fills, steady state alternates
one-forward-one-backward so the activation stash stays O(n_stages),
cooldown drains, then every stage applies its LOCAL optimizer shard.
No cross-stage traffic exists beyond the boundary hops.

Numerics contract (the parity test pins it): with the same init, the
accumulated gradient equals the single-program baseline's exactly —
each microbatch's backward uses the objective
``nll_sum_mb / count_total + (moe_aux_weight / num_mb) * aux_mb``
whose per-microbatch sum telescopes to the baseline loss
``nll_total / count_total + moe_aux_weight * aux_total / num_mb``
(count_total is known up front: targets are host data). Backward is
remat-style — each stage stashes only its chunk INPUT and the vjp
recomputes the chunk forward — so stash memory is
O(stash_depth * microbatch activation), with stash_depth <= n_stages
by the 1F1B invariant.

Optimizer locality: the update runs per stage on that stage's shard.
Anything inside the optax chain that wants a GLOBAL reduction (e.g.
clip_by_global_norm) sees only the local shard — use per-stage
clipping or a clip-free optimizer when cross-stage-exact optimizer
semantics matter (README "Pipeline-parallel training (MPMD)").
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import api as rt
from .._private.config import Config
from ..dag.channels import ShmChannel
from ..dag.edges import Edge
from ..dag.tcp_channel import TcpChannel
from ..exceptions import GetTimeoutError, RayTpuError
from ..parallel.schedule import (
    interleaved_1f1b,
    max_stash_depth,
    partition_layers,
    theoretical_efficiency,
    validate_schedule,
)

__all__ = ["MPMDPipeline", "MPMDPipelineError"]


class MPMDPipelineError(RayTpuError):
    """A pipeline step failed (stage death, channel timeout, protocol
    desync). The pipeline is broken afterwards — build a new one."""


# ---------------------------------------------------------------------------
# stage programs (jit-compiled inside the stage actor)
# ---------------------------------------------------------------------------

def _remat_body(cfg, body):
    import jax

    if not cfg.remat:
        return body
    if cfg.remat_policy == "dots":
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies
            .dots_with_no_batch_dims_saveable,
        )
    if cfg.remat_policy == "dots_flash":
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    "flash_out", "flash_lse"
                ),
            ),
        )
    return jax.checkpoint(body)


def _make_chunk_fwd(cfg, first: bool):
    """fwd(params, x) -> (y, aux_sum) for one chunk. `first` chunks
    take token ids and embed them; later chunks take activations.
    RoPE cos/sin recompute inside the jit from absolute positions —
    cheap next to the stack, and it keeps the channel payload to the
    activation alone."""
    import jax.numpy as jnp
    from jax import lax

    from ..models.llama import _layer, embed_tokens
    from ..ops.norms import rotary_embedding

    def fwd(params, x):
        b, t = x.shape[0], x.shape[1]
        if first:
            x = embed_tokens(cfg, params, x)
        positions = jnp.broadcast_to(jnp.arange(t), (b, t))
        cos, sin = rotary_embedding(
            positions, cfg.head_dim, cfg.rope_theta,
            getattr(cfg, "rope_scaling", None),
        )

        def body(xc, layer):
            return _layer(cfg, xc, layer, cos, sin, None, None)

        h, auxs = lax.scan(
            _remat_body(cfg, body), x, params["layers"]
        )
        return h, jnp.sum(auxs)

    return fwd


def _make_last_objective(cfg):
    """objective(params, x, targets, inv_count, aux_scale) for the
    LAST chunk: its layers + final norm + lm_head + masked xent. The
    scaling makes per-microbatch objectives sum to the exact baseline
    loss (see module docstring)."""
    import jax.numpy as jnp
    from jax import lax

    from ..models.llama import _layer, masked_xent, model_norm
    from ..ops.norms import rotary_embedding

    def objective(params, x, targets, inv_count, aux_scale):
        b, t = x.shape[0], x.shape[1]
        positions = jnp.broadcast_to(jnp.arange(t), (b, t))
        cos, sin = rotary_embedding(
            positions, cfg.head_dim, cfg.rope_theta,
            getattr(cfg, "rope_scaling", None),
        )

        def body(xc, layer):
            return _layer(cfg, xc, layer, cos, sin, None, None)

        h, auxs = lax.scan(
            _remat_body(cfg, body), x, params["layers"]
        )
        h = model_norm(cfg, h, params["final_norm"])
        logits = (h @ params["lm_head"]).astype(jnp.float32)
        nll, count = masked_xent(logits, targets)
        aux = jnp.sum(auxs)
        obj = nll * inv_count + aux_scale * aux
        return obj, (nll, count, aux)

    return objective


# ---------------------------------------------------------------------------
# the stage actor
# ---------------------------------------------------------------------------

class _PipelineStage:
    """One physical pipeline stage: owns its chunks' params + optimizer
    shard, its jitted programs, its channel endpoints, and executes its
    slice of the 1F1B schedule per `run_step` call."""

    def __init__(
        self,
        stage_idx: int,
        n_stages: int,
        cfg,
        chunk_specs: Sequence[Tuple[int, int, int]],
        n_chunks_total: int,
        num_microbatches: int,
        ops: Sequence[Tuple[str, int, int]],
        optimizer_factory: Optional[Callable],
        hop_timeout_s: float,
    ):
        self.stage = int(stage_idx)
        self.n_stages = int(n_stages)
        self.cfg = cfg
        self.chunk_specs = [tuple(s) for s in chunk_specs]
        self.V = int(n_chunks_total)
        self.num_mb = int(num_microbatches)
        self.ops = [tuple(op) for op in ops]
        self.hop_timeout = float(hop_timeout_s)
        self.aux_scale = float(
            getattr(cfg, "moe_aux_weight", 0.0)
        ) / self.num_mb
        self._optimizer = (
            optimizer_factory() if optimizer_factory else None
        )
        self._params: Dict[int, Any] = {}
        self._opt_state = None
        self._programs: Dict[str, Any] = {}
        self._edges: Dict[str, Dict[int, Optional[Edge]]] = {}
        # Session wiring: stage rank telemetry rides the same
        # per-(step, rank) records gang training uses, so doctor /
        # goodput / gang-skew read pipeline stages like data ranks.
        from .session import TrainContext, init_session

        init_session(
            TrainContext(
                world_rank=self.stage, world_size=self.n_stages
            )
        )

    # -- wiring --------------------------------------------------------
    def wire(self, fwd_in, fwd_out, bwd_in, bwd_out) -> int:
        """Install this stage's channel endpoints (dict: chunk ->
        Edge | None). Called once at build; edges are REUSED across
        every subsequent step — wiring is off the step path."""
        self._edges = {
            "fwd_in": dict(fwd_in),
            "fwd_out": dict(fwd_out),
            "bwd_in": dict(bwd_in),
            "bwd_out": dict(bwd_out),
        }
        return self.stage

    def set_params(self, chunk_params: Dict[int, Any]) -> int:
        """Install per-chunk param trees (host arrays), build the
        optimizer shard over ALL this stage's chunks."""
        import jax

        self._params = {
            int(c): jax.tree.map(jax.numpy.asarray, tree)
            for c, tree in chunk_params.items()
        }
        if self._optimizer is not None:
            self._opt_state = self._optimizer.init(self._params)
        self._build_programs()
        return self.stage

    def _build_programs(self) -> None:
        import jax

        from .._private import compile_watch

        # Every stage program registers with the compile watcher by
        # name (mpmd.s<stage>.<fwd|bwd>:<chunk> — bounded by the
        # pipeline topology): per-chunk fwd/bwd must compile once at
        # warmup and NEVER again, and a microbatch-shape drift that
        # re-traces a stage mid-training now convicts itself in
        # `doctor` verdict.compile instead of reading as a slow
        # stage.
        def _jit(key: str, fn):
            return compile_watch.instrument(
                f"mpmd.s{self.stage}.{key}", jax.jit(fn)
            )

        cfg = self.cfg
        for c, _lo, _hi in self.chunk_specs:
            first = c == 0
            last = c == self.V - 1
            if last:
                objective = _make_last_objective(cfg)

                def last_bwd(p, x, t, ic, ascale, _obj=objective):
                    return jax.value_and_grad(
                        _obj, argnums=(0, 1), has_aux=True
                    )(p, x, t, ic, ascale)

                self._programs[f"bwd:{c}"] = _jit(f"bwd:{c}", last_bwd)
            else:
                fwd = _make_chunk_fwd(cfg, first)
                self._programs[f"fwd:{c}"] = _jit(f"fwd:{c}", fwd)
                if first:

                    def first_bwd(p, tokens, gy, aux_ct, _fwd=fwd):
                        (y, aux), vjp = jax.vjp(
                            lambda pp: _fwd(pp, tokens), p
                        )
                        (dp,) = vjp((gy, aux_ct.astype(aux.dtype)))
                        return dp, aux

                    self._programs[f"bwd:{c}"] = _jit(f"bwd:{c}", first_bwd)
                else:

                    def mid_bwd(p, x, gy, aux_ct, _fwd=fwd):
                        (y, aux), vjp = jax.vjp(_fwd, p, x)
                        dp, dx = vjp((gy, aux_ct.astype(aux.dtype)))
                        return dp, dx, aux

                    self._programs[f"bwd:{c}"] = _jit(f"bwd:{c}", mid_bwd)
        self._programs["acc"] = _jit(
            "acc", lambda a, b: jax.tree.map(jax.numpy.add, a, b)
        )
        if self._optimizer is not None:
            import optax

            def opt_update(params, opt_state, grads):
                updates, new_opt = self._optimizer.update(
                    grads, opt_state, params
                )
                return optax.apply_updates(params, updates), new_opt

            self._programs["opt"] = _jit("opt", opt_update)

    # -- the step ------------------------------------------------------
    def run_step(
        self,
        step_index: int,
        tokens_mbs: Optional[List[np.ndarray]] = None,
        targets_mbs: Optional[List[np.ndarray]] = None,
    ) -> dict:
        """Execute this stage's 1F1B op list once: recv/compute/send
        per op, accumulate grads, then apply the local optimizer
        shard. Returns loss pieces + the per-op timing and edge-wait
        numbers pipebench's efficiency accounting reads."""
        import jax
        import jax.numpy as jnp

        t_wall0 = time.monotonic()
        V, last_c = self.V, self.V - 1
        stash: Dict[Tuple[int, int], Any] = {}
        stash_peak = 0
        grads: Dict[int, Any] = {}
        op_ms: Dict[str, List[float]] = {}
        # Loss pieces stay device-side until the schedule drains —
        # a float() per op would insert m extra D2H syncs into the
        # schedule's critical path.
        nll_parts: List[Any] = []
        cnt_parts: List[Any] = []
        aux_parts: List[Any] = []
        obj_parts: List[Any] = []
        inv_count = aux_scale_arr = None
        if targets_mbs is not None:
            count = float(
                sum(int((t >= 0).sum()) for t in targets_mbs)
            )
            inv_count = jnp.asarray(
                1.0 / max(count, 1.0), jnp.float32
            )
        aux_scale_arr = jnp.asarray(self.aux_scale, jnp.float32)

        def _time(key: str, t0: float) -> None:
            op_ms.setdefault(key, []).append(
                (time.monotonic() - t0) * 1e3
            )

        for kind, c, mb in self.ops:
            if kind == "F":
                if c == 0:
                    x = tokens_mbs[mb]
                else:
                    tag, x = self._recv("fwd_in", c, ("F", c, mb))
                stash[(c, mb)] = x
                stash_peak = max(stash_peak, len(stash))
                if c != last_c:
                    t0 = time.monotonic()
                    y, aux = self._programs[f"fwd:{c}"](
                        self._params[c], x
                    )
                    y = np.asarray(y)
                    _time(f"F:{c}", t0)
                    aux_parts.append(aux)
                    self._send("fwd_out", c, ("F", c + 1, mb), y)
                # Last chunk: forward happens inside its backward's
                # vjp (remat) — F just lands the stash.
            else:  # B
                x = stash.pop((c, mb))
                if c == last_c:
                    t0 = time.monotonic()
                    (obj, (nll, cnt, aux)), (dp, dx) = self._programs[
                        f"bwd:{c}"
                    ](
                        self._params[c], x, targets_mbs[mb],
                        inv_count, aux_scale_arr,
                    )
                    dx = np.asarray(dx)
                    _time(f"B:{c}", t0)
                    nll_parts.append(nll)
                    cnt_parts.append(cnt)
                    aux_parts.append(aux)
                    obj_parts.append(obj)
                    if c > 0:
                        self._send(
                            "bwd_out", c, ("B", c - 1, mb), dx
                        )
                elif c == 0:
                    tag, gy = self._recv("bwd_in", c, ("B", c, mb))
                    t0 = time.monotonic()
                    dp, _aux = self._programs[f"bwd:{c}"](
                        self._params[c], x,
                        jnp.asarray(gy), aux_scale_arr,
                    )
                    jax.block_until_ready(jax.tree.leaves(dp)[0])
                    _time(f"B:{c}", t0)
                else:
                    tag, gy = self._recv("bwd_in", c, ("B", c, mb))
                    t0 = time.monotonic()
                    dp, dx, _aux = self._programs[f"bwd:{c}"](
                        self._params[c], x,
                        jnp.asarray(gy), aux_scale_arr,
                    )
                    dx = np.asarray(dx)
                    _time(f"B:{c}", t0)
                    self._send("bwd_out", c, ("B", c - 1, mb), dx)
                grads[c] = (
                    dp if c not in grads
                    else self._programs["acc"](grads[c], dp)
                )
        if stash:
            raise MPMDPipelineError(
                f"stage {self.stage}: {len(stash)} unretired "
                "stashes after the schedule — schedule bug"
            )
        opt_ms = 0.0
        if self._optimizer is not None:
            t0 = time.monotonic()
            self._params, self._opt_state = self._programs["opt"](
                self._params, self._opt_state, grads
            )
            jax.block_until_ready(
                jax.tree.leaves(self._params)[0]
            )
            opt_ms = (time.monotonic() - t0) * 1e3

        nll_total = float(sum(float(x) for x in nll_parts))
        cnt_total = float(sum(float(x) for x in cnt_parts))
        aux_total = float(sum(float(x) for x in aux_parts))
        obj_total = float(sum(float(x) for x in obj_parts))
        wall_ms = (time.monotonic() - t_wall0) * 1e3
        busy_ms = (
            sum(sum(v) for v in op_ms.values()) + opt_ms
        )
        edges = [
            e.take_stats()
            for group in self._edges.values()
            for e in group.values()
            if e is not None
        ]
        # Session heartbeat: one per-(step, rank=stage) record with
        # send_wait/recv_wait phases (billed by Edge) riding the
        # metrics pipe — the doctor's bubble attribution.
        from .session import get_session

        session = get_session()
        if session is not None:
            session.report(
                {"step_ms": busy_ms, "pipeline_stage": self.stage}
            )
        return {
            "stage": self.stage,
            "nll": nll_total,
            "count": cnt_total,
            "aux": aux_total,
            "objective": obj_total,
            "busy_ms": round(busy_ms, 3),
            "opt_ms": round(opt_ms, 3),
            "wall_ms": round(wall_ms, 3),
            "op_ms": {
                k: [round(v, 3) for v in vals]
                for k, vals in op_ms.items()
            },
            "edges": edges,
            "stash_peak": stash_peak,
        }

    def _recv(self, group: str, chunk: int, want: tuple):
        edge = self._edges[group][chunk]
        record = edge.get_value(timeout=self.hop_timeout)
        tag, payload = record
        if tuple(tag) != want:
            raise MPMDPipelineError(
                f"stage {self.stage} edge {edge.name}: got record "
                f"{tag}, schedule expected {want}"
            )
        return tag, payload

    def _send(self, group: str, chunk: int, tag: tuple,
              payload) -> None:
        edge = self._edges[group][chunk]
        edge.put_value((tag, payload), timeout=self.hop_timeout)

    # -- params / checkpoints -----------------------------------------
    def get_params(self) -> Dict[int, Any]:
        return {
            c: jax_tree_to_numpy(tree)
            for c, tree in self._params.items()
        }

    def save(self, root: str, step: int,
             async_save: bool = True) -> str:
        from .checkpoint import save_checkpoint

        path = os.path.join(
            root, f"step-{step:08d}", f"stage-{self.stage}"
        )
        save_checkpoint(
            path,
            {"params": self._params, "opt_state": self._opt_state},
            metadata={
                "stage": self.stage,
                "chunks": [c for c, _l, _h in self.chunk_specs],
                "step": int(step),
            },
            async_save=async_save,
        )
        return path

    def wait_ckpt(self) -> None:
        """PR 4 durability barrier, stage-side: pending async saves
        must persist before the driver trusts the checkpoint."""
        from .checkpoint import wait_for_checkpoints

        wait_for_checkpoints()

    def restore(self, root: str, step: int) -> int:
        from .checkpoint import restore_checkpoint

        path = os.path.join(
            root, f"step-{step:08d}", f"stage-{self.stage}"
        )
        state = restore_checkpoint(
            path,
            {"params": self._params, "opt_state": self._opt_state},
        )
        self._params = state["params"]
        self._opt_state = state["opt_state"]
        return self.stage

    def ping(self) -> int:
        return self.stage


def jax_tree_to_numpy(tree):
    import jax

    return jax.tree.map(np.asarray, tree)


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

class MPMDPipeline:
    """Driver for MPMD pipeline-parallel training of the flagship
    Llama stack.

    Build once (spawns the stage actors, wires channel edges, installs
    params), then call `step(tokens, targets)` per global batch.
    Geometry: ``global batch = num_microbatches * microbatch_size``,
    layer partition from `partition_layers` (pass `layer_ms` /
    `embed_ms` / `head_ms` from bench.py's `fixed_ms_breakdown` to
    balance the asymmetric ends; uniform otherwise).
    """

    def __init__(
        self,
        cfg,
        n_stages: int,
        *,
        num_microbatches: int,
        microbatch_size: int,
        seq_len: int,
        chunks_per_stage: int = 1,
        optimizer_factory: Optional[Callable] = None,
        layer_ms: Optional[Sequence[float]] = None,
        embed_ms: float = 0.0,
        head_ms: float = 0.0,
        channel_depth: Optional[int] = None,
        hop_timeout_s: Optional[float] = None,
        step_timeout_s: Optional[float] = None,
        init_key: int = 0,
        params: Optional[dict] = None,
        num_cpus_per_stage: int = 1,
    ):
        if n_stages < 2:
            raise ValueError("MPMD pipeline needs >= 2 stages")
        config = Config.from_env()
        self.cfg = cfg
        self.n = int(n_stages)
        self.v = int(chunks_per_stage)
        self.V = self.n * self.v
        self.m = int(num_microbatches)
        self.mb = int(microbatch_size)
        self.seq = int(seq_len)
        self.depth = int(
            channel_depth or config.pipeline_channel_depth
        )
        self.hop_timeout = float(
            hop_timeout_s or config.pipeline_hop_timeout_s
        )
        self.step_timeout = float(
            step_timeout_s or config.pipeline_step_timeout_s
        )
        if isinstance(layer_ms, (int, float)):
            # bench.py's measured `layer_ms` is one number for a
            # homogeneous stack — broadcast it.
            layer_ms = [float(layer_ms)] * cfg.n_layers
        self.bounds = partition_layers(
            cfg.n_layers,
            self.V,
            layer_ms,
            embed_ms=embed_ms,
            head_ms=head_ms,
        )
        self.schedules = interleaved_1f1b(self.n, self.m, self.v)
        # Bounded-edge validation: a schedule too deep for the
        # configured channel depth must die HERE (ValueError naming
        # the depth), never as an all-stages hang at hop-timeout.
        validate_schedule(
            self.schedules, self.n, self.m, self.v,
            channel_depth=self.depth,
        )
        self.stash_bound = max(
            max_stash_depth(ops) for ops in self.schedules
        )
        self._broken = False
        self._edges_by_boundary: Dict[
            Tuple[int, str], Edge
        ] = {}
        self._spawn(optimizer_factory, num_cpus_per_stage)
        self._wire()
        self._install_params(params, init_key)
        self._step_index = 0

    # -- build ---------------------------------------------------------
    def _spawn(self, optimizer_factory, num_cpus: int) -> None:
        stage_cls = rt.remote(num_cpus=num_cpus)(_PipelineStage)
        chunk_of_stage = {
            s: [
                (c, *self.bounds[c])
                for c in range(s, self.V, self.n)
            ]
            for s in range(self.n)
        }
        self.stages = [
            stage_cls.remote(
                s,
                self.n,
                self.cfg,
                chunk_of_stage[s],
                self.V,
                self.m,
                self.schedules[s],
                optimizer_factory,
                self.hop_timeout,
            )
            for s in range(self.n)
        ]
        rt.get(
            [a.ping.remote() for a in self.stages], timeout=120
        )

    def _placements(self) -> Dict[int, Optional[str]]:
        """stage index -> node id hex (shared compiled-DAG placement
        wait — a just-created actor may still be leasing)."""
        from ..dag.compiled import wait_actor_placements

        by_id = wait_actor_placements(
            [a for a in self.stages], timeout=60.0
        )
        return {
            s: by_id[a.actor_id.binary()]
            for s, a in enumerate(self.stages)
        }

    def _channel_capacity(self) -> int:
        import jax.numpy as jnp

        itemsize = jnp.dtype(self.cfg.dtype).itemsize
        record = (
            self.mb * self.seq * self.cfg.dim * itemsize + 4096
        )
        # Bounded depth IS the backpressure: a stage can run at most
        # `depth` records ahead of its consumer before put() blocks.
        return self.depth * record + 8192

    def _wire(self) -> None:
        placements = self._placements()
        capacity = self._channel_capacity()

        def new_edge(boundary: int, direction: str,
                     src: int, dst: int) -> Edge:
            same = placements.get(src) == placements.get(dst)
            chan = (
                ShmChannel(capacity)
                if same
                else TcpChannel(capacity)
            )
            edge = Edge(
                chan,
                f"s{src}->s{dst}:b{boundary}",
                direction,
            )
            self._edges_by_boundary[(boundary, direction)] = edge
            return edge

        fwd_in: List[Dict[int, Optional[Edge]]] = [
            {} for _ in range(self.n)
        ]
        fwd_out = [dict() for _ in range(self.n)]
        bwd_in = [dict() for _ in range(self.n)]
        bwd_out = [dict() for _ in range(self.n)]
        for c in range(self.V):
            src, dst = c % self.n, (c + 1) % self.n
            if c < self.V - 1:
                f_edge = new_edge(c, "fwd", src, dst)
                fwd_out[src][c] = f_edge
                fwd_in[dst][c + 1] = f_edge
                g_edge = new_edge(c, "grad", dst, src)
                bwd_out[dst][c + 1] = g_edge
                bwd_in[src][c] = g_edge
            # chunk 0 has no fwd_in/bwd_out; last chunk no
            # fwd_out/bwd_in — run_step never touches those keys.
        rt.get(
            [
                a.wire.remote(
                    fwd_in[s], fwd_out[s], bwd_in[s], bwd_out[s]
                )
                for s, a in enumerate(self.stages)
            ],
            timeout=120,
        )

    def _install_params(self, params, init_key) -> None:
        if params is None:
            import jax

            from ..models.llama import init_params

            params = init_params(
                jax.random.PRNGKey(init_key), self.cfg
            )
        params = jax_tree_to_numpy(params)
        per_stage: List[Dict[int, Any]] = [
            {} for _ in range(self.n)
        ]
        for c, (lo, hi) in enumerate(self.bounds):
            tree: Dict[str, Any] = {
                "layers": {
                    k: v[lo:hi]
                    for k, v in params["layers"].items()
                }
            }
            if c == 0:
                tree["embed"] = params["embed"]
            if c == self.V - 1:
                tree["final_norm"] = params["final_norm"]
                tree["lm_head"] = params["lm_head"]
            per_stage[c % self.n][c] = tree
        rt.get(
            [
                a.set_params.remote(per_stage[s])
                for s, a in enumerate(self.stages)
            ],
            timeout=300,
        )

    # -- stepping ------------------------------------------------------
    def step(self, tokens: np.ndarray,
             targets: np.ndarray) -> dict:
        """One global-batch training step. tokens/targets: [B, T]
        host arrays with B == num_microbatches * microbatch_size.
        Returns {"loss", "stages": [per-stage telemetry]}; raises
        MPMDPipelineError (never hangs) when a stage dies or a
        channel times out."""
        if self._broken:
            raise MPMDPipelineError(
                "pipeline is broken (a previous step failed)"
            )
        B = tokens.shape[0]
        if B != self.m * self.mb:
            raise ValueError(
                f"batch {B} != num_microbatches {self.m} x "
                f"microbatch_size {self.mb}"
            )
        tokens_mbs = [
            np.ascontiguousarray(
                tokens[i * self.mb : (i + 1) * self.mb]
            )
            for i in range(self.m)
        ]
        targets_mbs = [
            np.ascontiguousarray(
                targets[i * self.mb : (i + 1) * self.mb]
            )
            for i in range(self.m)
        ]
        self._step_index += 1
        refs = []
        for s, actor in enumerate(self.stages):
            refs.append(
                actor.run_step.remote(
                    self._step_index,
                    tokens_mbs if s == 0 else None,
                    targets_mbs if s == self.n - 1 else None,
                )
            )
        results = self._gather(refs)
        last = results[self.n - 1]
        count = max(last["count"], 1.0)
        aux_total = sum(r["aux"] for r in results)
        aux_w = float(getattr(self.cfg, "moe_aux_weight", 0.0))
        loss = (
            last["nll"] / count + aux_w * aux_total / self.m
        )
        return {"loss": loss, "stages": results}

    def _gather(self, refs) -> List[dict]:
        """Collect every stage's result; the FIRST failure aborts the
        pipeline: all channel edges close (same-host shm peers
        unblock with ChannelClosedError immediately instead of
        waiting out hop timeouts; cross-host TCP stages that stay
        blocked past the drain deadline are force-killed), then
        raises with the root cause. Bounded by step_timeout + drain
        end to end."""
        deadline = time.monotonic() + self.step_timeout
        results: List[Optional[dict]] = [None] * len(refs)
        pending = dict(enumerate(refs))
        first_err: Optional[BaseException] = None
        while pending and time.monotonic() < deadline:
            for i in list(pending):
                try:
                    results[i] = rt.get(pending[i], timeout=0.25)
                    del pending[i]
                except GetTimeoutError:
                    continue
                except Exception as e:  # noqa: BLE001 — stage death
                    first_err = first_err or e
                    del pending[i]
                    self._abort()
            if first_err:
                # Straight to the bounded drain + force-kill below —
                # polling stuck survivors here would stretch recovery
                # to hop/step timeouts instead of the 15s drain.
                break
        if pending and first_err is None:
            first_err = MPMDPipelineError(
                f"step exceeded step_timeout_s={self.step_timeout:g} "
                f"with {len(pending)} stage(s) outstanding"
            )
            self._abort()
        if first_err is not None:
            # Same-host edges are closed (ShmChannel's shared flag
            # unblocks peers immediately); drain the survivors so no
            # ref leaks.
            drain_deadline = time.monotonic() + 15.0
            stuck: List[int] = []
            for i in list(pending):
                try:
                    rt.get(
                        pending[i],
                        timeout=max(
                            0.1, drain_deadline - time.monotonic()
                        ),
                    )
                except GetTimeoutError:
                    stuck.append(i)
                except Exception:  # noqa: BLE001 — draining
                    pass
            # A stage still blocked past the drain deadline is on a
            # CROSS-HOST edge: the driver's TcpChannel copy owns no
            # socket (roles bind on first use), so edge.close() above
            # couldn't reach it — force-kill the actor; its dying
            # sockets unblock ITS peers in turn.
            for i in stuck:
                try:
                    rt.kill(self.stages[i])
                except Exception:  # noqa: BLE001 — teardown
                    pass
                try:
                    rt.get(pending[i], timeout=10)
                except Exception:  # noqa: BLE001 — draining
                    pass
            raise MPMDPipelineError(
                f"pipeline step failed: {first_err!r}"
            ) from first_err
        return results  # type: ignore[return-value]

    def _abort(self) -> None:
        self._broken = True
        for edge in self._edges_by_boundary.values():
            try:
                edge.close()
            except Exception:  # noqa: BLE001 — teardown
                pass

    # -- checkpoints (PR 4 async barrier compose) ---------------------
    def save_checkpoint(self, root: str,
                        async_save: bool = True) -> List[str]:
        """Each stage saves its shard (params + optimizer state);
        with async_save the host snapshot happens now and persistence
        overlaps the next steps — `wait_for_checkpoints()` is the
        durability barrier."""
        return rt.get(
            [
                a.save.remote(root, self._step_index, async_save)
                for a in self.stages
            ],
            timeout=300,
        )

    def wait_for_checkpoints(self) -> None:
        rt.get(
            [a.wait_ckpt.remote() for a in self.stages],
            timeout=600,
        )

    def restore_checkpoint(self, root: str, step: int) -> None:
        rt.get(
            [a.restore.remote(root, step) for a in self.stages],
            timeout=300,
        )
        self._step_index = int(step)

    # -- introspection -------------------------------------------------
    def collect_params(self) -> dict:
        """Reassemble the full model tree from the stage shards
        (tests / export; the layer stack concatenates in chunk
        order)."""
        per_stage = rt.get(
            [a.get_params.remote() for a in self.stages],
            timeout=300,
        )
        by_chunk: Dict[int, Any] = {}
        for shard in per_stage:
            by_chunk.update(shard)
        layers = {
            k: np.concatenate(
                [by_chunk[c]["layers"][k] for c in range(self.V)]
            )
            for k in by_chunk[0]["layers"]
        }
        return {
            "embed": by_chunk[0]["embed"],
            "layers": layers,
            "final_norm": by_chunk[self.V - 1]["final_norm"],
            "lm_head": by_chunk[self.V - 1]["lm_head"],
        }

    def theoretical_efficiency(self) -> float:
        return theoretical_efficiency(self.n, self.m, self.v)

    def shutdown(self) -> None:
        for edge in self._edges_by_boundary.values():
            try:
                edge.close()
                edge.unlink()
            except Exception:  # noqa: BLE001 — teardown
                pass
        for actor in getattr(self, "stages", []):
            try:
                rt.kill(actor)
            except Exception:  # noqa: BLE001 — teardown
                pass
