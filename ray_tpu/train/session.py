"""Per-worker training session: report(), get_context(), checkpoints.

Mirrors the reference's _TrainSession surface (reference:
train/_internal/session.py:111 — a per-worker session object; user code
calls train.report(metrics, checkpoint=...):667, get_context, and
get_checkpoint:754 for restore)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: Optional[str] = None
    mesh: Any = None  # realized jax Mesh for this gang


class _Session:
    def __init__(self, context: TrainContext, result_callback=None):
        self.context = context
        self.results: List[Dict[str, Any]] = []
        self.latest_checkpoint: Optional[str] = None
        self._result_callback = result_callback
        self._lock = threading.Lock()

    def report(
        self, metrics: Dict[str, Any], checkpoint: Optional[str] = None
    ) -> None:
        with self._lock:
            self.results.append(dict(metrics))
            if checkpoint is not None:
                self.latest_checkpoint = checkpoint
        if self._result_callback is not None:
            self._result_callback(metrics, checkpoint)


_session_holder = threading.local()


def init_session(context: TrainContext, result_callback=None) -> _Session:
    session = _Session(context, result_callback)
    _session_holder.session = session
    return session


def clear_session() -> None:
    _session_holder.session = None


def get_session() -> Optional[_Session]:
    return getattr(_session_holder, "session", None)


def report(metrics: Dict[str, Any], checkpoint: Optional[str] = None) -> None:
    """Report metrics (and optionally a checkpoint dir) from the train
    loop (reference: train.report, session.py:667)."""
    session = get_session()
    if session is None:
        raise RuntimeError(
            "report() called outside a training session"
        )
    session.report(metrics, checkpoint)


def get_context() -> TrainContext:
    session = get_session()
    if session is None:
        return TrainContext()
    return session.context


def get_checkpoint() -> Optional[str]:
    session = get_session()
    return session.latest_checkpoint if session else None
