"""Per-worker training session: report(), get_context(), checkpoints.

Mirrors the reference's _TrainSession surface (reference:
train/_internal/session.py:111 — a per-worker session object; user code
calls train.report(metrics, checkpoint=...):667, get_context, and
get_checkpoint:754 for restore)."""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .._private import step_telemetry

_CKPT_MARKER = ".latest_checkpoint"


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: Optional[str] = None
    mesh: Any = None  # realized jax Mesh for this gang


class _Session:
    def __init__(
        self,
        context: TrainContext,
        result_callback=None,
        dataset_shards: Optional[Dict[str, Any]] = None,
    ):
        self.context = context
        self.results: List[Dict[str, Any]] = []
        self.latest_checkpoint: Optional[str] = None
        self._result_callback = result_callback
        self.dataset_shards = dataset_shards or {}
        self._lock = threading.Lock()
        # Step telemetry: report() is the per-step heartbeat of every
        # train loop, so it doubles as the step boundary — the wall
        # interval between consecutive reports, minus the wait phases
        # the data/H2D/checkpoint layers accumulated in that window,
        # is the step's own time.
        self._step_index = 0
        self._last_report_t = time.monotonic()
        # Drop phases accumulated on this thread BEFORE the session
        # existed (warmup/validation passes over instrumented
        # iterators): step 1 must not inherit their stall time.
        step_telemetry.take_phases()

    def report(
        self, metrics: Dict[str, Any], checkpoint: Optional[str] = None
    ) -> None:
        now = time.monotonic()
        with self._lock:
            self.results.append(dict(metrics))
            if checkpoint is not None:
                self.latest_checkpoint = checkpoint
                self._persist_marker(checkpoint)
            self._step_index += 1
            step = self._step_index
            wall_ms = (now - self._last_report_t) * 1e3
            self._last_report_t = now
        # An explicit step_ms metric (the loop timed its own step) wins
        # over the wall-minus-waits derivation.
        step_ms = metrics.get("step_ms")
        try:
            step_ms = None if step_ms is None else float(step_ms)
        except (TypeError, ValueError):
            step_ms = None
        # The first report's wall interval starts at session
        # construction, so everything train_func did before its loop
        # (model build, dataset setup) is inside it — a derived
        # step_ms would be setup time, not a step. Flag it so the
        # head's stats/skew (and the chrome trace) exclude it instead
        # of reporting setup noise as the cluster's max skew.
        warmup = step == 1 and step_ms is None
        step_telemetry.report_step(
            step,
            rank=self.context.world_rank,
            step_ms=step_ms,
            wall_ms=wall_ms,
            extra={"warmup": 1} if warmup else None,
        )
        if self._result_callback is not None:
            self._result_callback(metrics, checkpoint)

    def _persist_marker(self, checkpoint: str) -> None:
        """Record the latest checkpoint path in the trial dir so a
        restarted attempt (trainer retry / resumed experiment) can
        restore from it (reference: backend_executor._restart:759
        resumes from the latest tracked checkpoint)."""
        trial_dir = self.context.trial_dir
        if not trial_dir:
            return
        tmp = os.path.join(trial_dir, _CKPT_MARKER + ".tmp")
        try:
            with open(tmp, "w") as f:
                f.write(checkpoint)
            os.replace(tmp, os.path.join(trial_dir, _CKPT_MARKER))
        except OSError:
            pass


_session_holder = threading.local()


def init_session(
    context: TrainContext,
    result_callback=None,
    dataset_shards: Optional[Dict[str, Any]] = None,
) -> _Session:
    session = _Session(context, result_callback, dataset_shards)
    if context.trial_dir:
        # A retrying attempt in the same process may race its failed
        # predecessor's async checkpoint write: settle pending saves
        # before judging which marker paths exist on disk.
        from .checkpoint import wait_for_checkpoints

        wait_for_checkpoints()
        marker = os.path.join(context.trial_dir, _CKPT_MARKER)
        try:
            with open(marker) as f:
                path = f.read().strip()
            if path and os.path.exists(path):
                session.latest_checkpoint = path
        except OSError:
            pass
    _session_holder.session = session
    return session


def clear_session() -> None:
    _session_holder.session = None


def get_session() -> Optional[_Session]:
    return getattr(_session_holder, "session", None)


def report(metrics: Dict[str, Any], checkpoint: Optional[str] = None) -> None:
    """Report metrics (and optionally a checkpoint dir) from the train
    loop (reference: train.report, session.py:667)."""
    session = get_session()
    if session is None:
        raise RuntimeError(
            "report() called outside a training session"
        )
    session.report(metrics, checkpoint)


def get_context() -> TrainContext:
    session = get_session()
    if session is None:
        return TrainContext()
    return session.context


def get_checkpoint() -> Optional[str]:
    session = get_session()
    return session.latest_checkpoint if session else None


def get_dataset_shard(name: str = "train"):
    """This worker's streaming shard of the dataset passed to the
    trainer (reference: train.get_dataset_shard, session.py:1067 — a
    DataIterator over this rank's split)."""
    session = get_session()
    if session is None or name not in session.dataset_shards:
        raise KeyError(
            f"no dataset shard {name!r}; pass datasets={{...}} to the "
            "trainer"
        )
    return session.dataset_shards[name]


def get_device_batches(
    name: str = "train",
    *,
    mesh,
    batch_size: int = 256,
    batch_format: str = "numpy",
    drop_last: bool = False,
    prefetch_batches: int = 2,
    buffer_size: int = 2,
    logical_axes=("batch",),
    rules=None,
):
    """This rank's shard as device-resident batches with the whole
    overlap pipeline engaged: a background thread resolves and formats
    host batches `prefetch_batches` ahead (DataIterator.iter_batches),
    and `buffer_size` of them are device_put ahead of consumption so
    batch N+1 is on the mesh before step N retires. The train loop's
    only remaining critical-path work is the step itself."""
    from ..parallel.sharding import ACT_RULES
    from .train_step import prefetch_to_device

    shard = get_dataset_shard(name)
    batches = shard.iter_batches(
        batch_size=batch_size,
        batch_format=batch_format,
        drop_last=drop_last,
        prefetch_batches=prefetch_batches,
    )
    return prefetch_to_device(
        batches,
        mesh,
        buffer_size=buffer_size,
        logical_axes=logical_axes,
        rules=rules if rules is not None else ACT_RULES,
    )
