"""ObjectRef — a future naming an immutable object in the cluster.

Mirrors the reference's ObjectRef (reference: python/ray/_raylet.pyx:269
ObjectRef class): holds the binary object id, supports `get`-via-API,
equality/hashing by id, and releases its reference on garbage
collection so the owner can free the object (reference:
core_worker/reference_count.h owner-based refcounting).
"""

from __future__ import annotations

from ._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: ObjectID, owner=None, skip_adding_ref=False):
        self._id = object_id
        self._owner = owner
        if owner is not None and not skip_adding_ref:
            owner.add_local_ref(object_id)

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        owner = self._owner
        if owner is not None:
            try:
                owner.remove_local_ref(self._id)
            except Exception:
                pass

    def __reduce__(self):
        # Refs serialized into task args / object values re-attach to
        # the receiving process's worker on deserialization. A ref
        # escaping its owner must first be globally visible: direct
        # transport results live only in the owner's futures until
        # published to the daemon's object table.
        owner = self._owner
        if owner is not None:
            visible = getattr(owner, "ensure_globally_visible", None)
            if visible is not None:
                visible(self._id)
        return (_deserialize_ref, (self._id.binary(),))

    # `await ref` support for async drivers.
    def __await__(self):
        from . import api

        result = yield from _async_get(self).__await__()
        return result

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures
        import threading

        from . import api

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(api.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut


async def _async_get(ref: ObjectRef):
    import asyncio

    return await asyncio.wrap_future(ref.future())


def _deserialize_ref(binary: bytes) -> ObjectRef:
    from ._private.worker import global_worker

    oid = ObjectID(binary)
    worker = global_worker()
    if worker is not None:
        worker.notify_borrowed_ref(oid)
        return ObjectRef(oid, owner=worker)
    return ObjectRef(oid)
