"""ObjectRef — a future naming an immutable object in the cluster.

Mirrors the reference's ObjectRef (reference: python/ray/_raylet.pyx:269
ObjectRef class): holds the binary object id, supports `get`-via-API,
equality/hashing by id, and releases its reference on garbage
collection so the owner can free the object (reference:
core_worker/reference_count.h owner-based refcounting).
"""

from __future__ import annotations

from ._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: ObjectID, owner=None, skip_adding_ref=False):
        self._id = object_id
        self._owner = owner
        if owner is not None and not skip_adding_ref:
            owner.add_local_ref(object_id)

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        owner = self._owner
        if owner is not None:
            try:
                owner.remove_local_ref(self._id)
            except Exception:
                pass

    def __reduce__(self):
        # Refs serialized into task args / object values re-attach to
        # the receiving process's worker on deserialization. A ref
        # escaping its owner must first be globally visible: direct
        # transport results live only in the owner's futures until
        # published to the daemon's object table.
        owner = self._owner
        if owner is not None:
            visible = getattr(owner, "ensure_globally_visible", None)
            if visible is not None:
                visible(self._id)
        return (_deserialize_ref, (self._id.binary(),))

    # `await ref` support for async drivers.
    def __await__(self):
        from . import api

        result = yield from _async_get(self).__await__()
        return result

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures
        import threading

        from . import api

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(api.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut


async def _async_get(ref: ObjectRef):
    import asyncio

    return await asyncio.wrap_future(ref.future())


class ObjectRefGenerator:
    """Iterator over the ObjectRefs a generator task produces.

    Mirrors the reference's streaming/dynamic generator protocol
    (reference: python/ray/_raylet.pyx:269 ObjectRefGenerator;
    remote_function.py:385-391 num_returns="dynamic"/"streaming"):

    - Item object ids are **deterministic** — item *i* is
      ``ObjectID.for_return(task_id, i + 2)`` (index 1 is the task's
      primary return, which doubles as the completion marker carrying
      the final item count, stored by the worker AFTER every item is
      sealed). No extra control traffic is needed to stream.
    - Streaming mode: ``__next__`` blocks until item *i* is sealed or
      the completion marker lands (count known -> StopIteration, task
      error -> raised here).
    - Dynamic mode: the completion marker's VALUE is this generator
      (count pre-resolved), so ``get(ref)`` on a dynamic task returns
      an ObjectRefGenerator, per the reference's API.
    """

    def __init__(self, task_id, owner=None, count=None, primary_ref=None):
        self._task_id = task_id
        self._owner = owner
        self._count = count
        self._index = 0
        #: Held for the generator's lifetime while streaming: dropping
        #: the last local ref to the completion marker would release
        #: its owner-side future (and eventually the daemon entry)
        #: while __next__ still needs it.
        self._primary_ref: ObjectRef | None = primary_ref

    def _ref(self, object_id: ObjectID) -> ObjectRef:
        return ObjectRef(object_id, owner=self._owner)

    def _item_id(self, i: int) -> ObjectID:
        return ObjectID.for_return(self._task_id, i + 2)

    @property
    def completed_ref(self) -> ObjectRef:
        """Ref of the completion marker (resolves to the item count
        once the whole generator has run; errors if the task failed)."""
        if self._primary_ref is None:
            self._primary_ref = self._ref(
                ObjectID.for_return(self._task_id, 1)
            )
        return self._primary_ref

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        if self._owner is None:
            from ._private.worker import global_worker

            self._owner = global_worker()
        if self._count is not None:
            if self._index >= self._count:
                raise StopIteration
            ref = self._ref(self._item_id(self._index))
            self._index += 1
            return ref
        item = self._ref(self._item_id(self._index))
        primary = self.completed_ref
        while True:
            ready, _ = self._owner.wait(
                [item, primary], num_returns=1, timeout=30.0
            )
            if item in ready:
                self._index += 1
                return item
            if primary in ready:
                error = self._owner.peek_object_error(primary.id())
                if error is not None:
                    # Mid-stream failure: drain the items the worker
                    # sealed before erroring (their count rides in the
                    # payload), then re-raise the task's error.
                    import pickle as _pickle

                    emitted = _pickle.loads(error).get(
                        "items_emitted", 0
                    )
                    if self._index < (emitted or 0):
                        self._index += 1
                        return item
                    self._owner.get([primary])  # raises the error
                # Worker seals the marker after the last item, so by
                # now either index < count (item is sealed) or we are
                # past the end.
                marker = self._owner.get([primary])[0]
                self._count = (
                    marker._count
                    if isinstance(marker, ObjectRefGenerator)
                    else int(marker)
                )
                if self._index >= self._count:
                    raise StopIteration
                self._index += 1
                return item

    next = __next__

    def __reduce__(self):
        return (
            _deserialize_generator,
            (self._task_id.binary(), self._count),
        )

    def __repr__(self):
        return (
            f"ObjectRefGenerator(task={self._task_id.hex()}, "
            f"count={self._count}, index={self._index})"
        )


def _deserialize_generator(task_binary: bytes, count):
    from ._private.ids import TaskID
    from ._private.worker import global_worker

    return ObjectRefGenerator(
        TaskID(task_binary), owner=global_worker(), count=count
    )


def _deserialize_ref(binary: bytes) -> ObjectRef:
    from ._private.worker import global_worker

    oid = ObjectID(binary)
    worker = global_worker()
    if worker is not None:
        worker.notify_borrowed_ref(oid)
        return ObjectRef(oid, owner=worker)
    return ObjectRef(oid)
