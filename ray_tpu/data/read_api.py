"""Dataset creation APIs.

Reference: python/ray/data/read_api.py — 35 read/from constructors;
the ones that matter for TPU input pipelines are implemented natively
(range/items + csv/json/jsonl/parquet/text/binary/numpy/tfrecords via
one read task per file, and from_numpy/from_pandas/from_arrow/
from_torch/from_huggingface in-memory converters), the hosted-service
connector zoo (BigQuery/Mongo/Iceberg/...) is out of scope and
documented as such.
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, Dict, List

import numpy as np

from .dataset import Dataset
from .executor import ReadStage


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(
                sorted(
                    os.path.join(path, f)
                    for f in os.listdir(path)
                    if not f.startswith(".")
                )
            )
        elif any(c in path for c in "*?["):
            out.extend(sorted(_glob.glob(path)))
        else:
            out.append(path)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    if parallelism <= 0:
        parallelism = min(32, max(1, n // 1000 or 1))
    step = -(-n // parallelism)
    tasks = []
    for start in builtins.range(0, n, step):
        end = min(n, start + step)
        tasks.append(
            lambda s=start, e=end: [
                {"id": i} for i in builtins.range(s, e)
            ]
        )
    return Dataset([ReadStage(tasks, "read_range")])


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    rows = [
        item if isinstance(item, dict) else {"item": item}
        for item in items
    ]
    if parallelism <= 0:
        parallelism = min(32, max(1, len(rows) // 100 or 1))
    step = -(-len(rows) // parallelism) if rows else 1
    chunks = [
        rows[i : i + step] for i in builtins.range(0, len(rows), step)
    ] or [[]]
    return Dataset(
        [ReadStage([lambda c=c: c for c in chunks], "from_items")]
    )


def from_numpy(arrays: Dict[str, np.ndarray]) -> Dataset:
    n = len(next(iter(arrays.values())))
    rows = [
        {k: v[i] for k, v in arrays.items()} for i in builtins.range(n)
    ]
    return from_items(rows)


def _file_read_dataset(paths, read_one, name: str) -> Dataset:
    files = _expand_paths(paths)
    return Dataset(
        [ReadStage([lambda p=p: read_one(p) for p in files], name)]
    )


def read_csv(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path).to_pylist()

    return _file_read_dataset(paths, read_one, "read_csv")


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        import json

        with open(path) as f:
            text = f.read().strip()
        if not text:
            return []
        if text[0] == "[":
            return json.loads(text)
        return [json.loads(line) for line in text.splitlines() if line]

    return _file_read_dataset(paths, read_one, "read_json")


def read_parquet(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        import pyarrow.parquet as pq

        return pq.read_table(path).to_pylist()

    return _file_read_dataset(paths, read_one, "read_parquet")


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        with open(path) as f:
            return [{"text": line.rstrip("\n")} for line in f]

    return _file_read_dataset(paths, read_one, "read_text")


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        with open(path, "rb") as f:
            return [{"path": path, "bytes": f.read()}]

    return _file_read_dataset(paths, read_one, "read_binary_files")


def read_numpy(paths, *, column: str = "data") -> Dataset:
    """.npy (one row per outer index) and .npz (one column per
    array) files (reference: read_api.py read_numpy)."""

    def read_one(path: str):
        loaded = np.load(path, allow_pickle=False)
        if isinstance(loaded, np.ndarray):
            return [{column: row} for row in loaded]
        arrays = {k: loaded[k] for k in loaded.files}
        n = len(next(iter(arrays.values())))
        return [
            {k: v[i] for k, v in arrays.items()}
            for i in builtins.range(n)
        ]

    return _file_read_dataset(paths, read_one, "read_numpy")


def read_tfrecords(paths, *, raw: bool = False) -> Dataset:
    """TFRecord files of tf.train.Example payloads, no tensorflow
    required (reference: read_api.py read_tfrecords; container/proto
    codec in data/tfrecords.py). `raw=True` yields undecoded
    {"bytes": payload} rows (webdataset-style passthrough)."""

    def read_one(path: str):
        from . import tfrecords as tfr

        if raw:
            return [
                {"bytes": payload}
                for payload in tfr.read_records(path)
            ]
        return [
            tfr.decode_example(payload)
            for payload in tfr.read_records(path)
        ]

    return _file_read_dataset(paths, read_one, "read_tfrecords")


def from_pandas(dfs) -> Dataset:
    """One block per DataFrame (reference: read_api.py from_pandas)."""
    import pandas as pd

    if isinstance(dfs, pd.DataFrame):
        dfs = [dfs]
    chunks = [df.to_dict("records") for df in dfs]
    return Dataset(
        [ReadStage([lambda c=c: c for c in chunks], "from_pandas")]
    )


def from_arrow(tables) -> Dataset:
    """One block per pyarrow Table (reference: from_arrow)."""
    import pyarrow as pa

    if isinstance(tables, pa.Table):
        tables = [tables]
    chunks = [table.to_pylist() for table in tables]
    return Dataset(
        [ReadStage([lambda c=c: c for c in chunks], "from_arrow")]
    )


def from_torch(torch_dataset, *, parallelism: int = -1) -> Dataset:
    """Map-style torch Dataset -> rows {"item": sample} (reference:
    from_torch). Materializes through __getitem__ on read workers in
    index ranges."""
    n = len(torch_dataset)
    if parallelism <= 0:
        parallelism = min(8, max(1, n // 1000 or 1))
    step = -(-n // parallelism) if n else 1

    def read_range(start: int, end: int):
        return [
            {"item": torch_dataset[i]}
            for i in builtins.range(start, end)
        ]

    tasks = [
        lambda s=s, e=min(n, s + step): read_range(s, e)
        for s in builtins.range(0, n, step)
    ] or [lambda: []]
    return Dataset([ReadStage(tasks, "from_torch")])


def from_huggingface(hf_dataset) -> Dataset:
    """datasets.Dataset -> one block per shard-ish chunk (reference:
    from_huggingface). Works with any object exposing __len__ +
    __getitem__(int) -> dict (the HF arrow-backed map-style API)."""
    n = len(hf_dataset)
    step = max(1, -(-n // 8))
    chunks = []
    for start in builtins.range(0, n, step):
        end = min(n, start + step)
        chunks.append(
            lambda s=start, e=end: [
                dict(hf_dataset[i]) for i in builtins.range(s, e)
            ]
        )
    return Dataset(
        [ReadStage(chunks or [lambda: []], "from_huggingface")]
    )
