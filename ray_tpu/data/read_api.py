"""Dataset creation APIs.

Reference: python/ray/data/read_api.py — 35 read/from constructors;
the ones that matter for TPU input pipelines are implemented natively
(range/items/numpy + csv/json/jsonl/parquet/text/binary via one read
task per file), the exotic connector zoo (BigQuery/Mongo/Iceberg/...)
is out of scope and documented as such.
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, Dict, List

import numpy as np

from .dataset import Dataset
from .executor import ReadStage


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(
                sorted(
                    os.path.join(path, f)
                    for f in os.listdir(path)
                    if not f.startswith(".")
                )
            )
        elif any(c in path for c in "*?["):
            out.extend(sorted(_glob.glob(path)))
        else:
            out.append(path)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    if parallelism <= 0:
        parallelism = min(32, max(1, n // 1000 or 1))
    step = -(-n // parallelism)
    tasks = []
    for start in builtins.range(0, n, step):
        end = min(n, start + step)
        tasks.append(
            lambda s=start, e=end: [
                {"id": i} for i in builtins.range(s, e)
            ]
        )
    return Dataset([ReadStage(tasks, "read_range")])


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    rows = [
        item if isinstance(item, dict) else {"item": item}
        for item in items
    ]
    if parallelism <= 0:
        parallelism = min(32, max(1, len(rows) // 100 or 1))
    step = -(-len(rows) // parallelism) if rows else 1
    chunks = [
        rows[i : i + step] for i in builtins.range(0, len(rows), step)
    ] or [[]]
    return Dataset(
        [ReadStage([lambda c=c: c for c in chunks], "from_items")]
    )


def from_numpy(arrays: Dict[str, np.ndarray]) -> Dataset:
    n = len(next(iter(arrays.values())))
    rows = [
        {k: v[i] for k, v in arrays.items()} for i in builtins.range(n)
    ]
    return from_items(rows)


def _file_read_dataset(paths, read_one, name: str) -> Dataset:
    files = _expand_paths(paths)
    return Dataset(
        [ReadStage([lambda p=p: read_one(p) for p in files], name)]
    )


def read_csv(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path).to_pylist()

    return _file_read_dataset(paths, read_one, "read_csv")


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        import json

        with open(path) as f:
            text = f.read().strip()
        if not text:
            return []
        if text[0] == "[":
            return json.loads(text)
        return [json.loads(line) for line in text.splitlines() if line]

    return _file_read_dataset(paths, read_one, "read_json")


def read_parquet(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        import pyarrow.parquet as pq

        return pq.read_table(path).to_pylist()

    return _file_read_dataset(paths, read_one, "read_parquet")


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        with open(path) as f:
            return [{"text": line.rstrip("\n")} for line in f]

    return _file_read_dataset(paths, read_one, "read_text")


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    def read_one(path: str):
        with open(path, "rb") as f:
            return [{"path": path, "bytes": f.read()}]

    return _file_read_dataset(paths, read_one, "read_binary_files")
