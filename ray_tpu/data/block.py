"""Block format helpers.

Reference: python/ray/data/block.py — a Dataset is a list of blocks
held in the object store. The reference's canonical block is an Arrow
table; here the canonical block is a **list of dict rows**, with
column-major numpy batches as the exchange format for map_batches /
iter_batches — numpy feeds `jax.numpy.asarray` zero-copy, which is the
TPU-side consumer that matters.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List

import numpy as np

try:  # reference's canonical block format; optional here
    import pyarrow as _pa
except ImportError:
    _pa = None
try:
    import pandas as _pd
except ImportError:
    _pd = None

Block = List[dict]
Batch = Dict[str, np.ndarray]


def rows_to_batch(rows: Block) -> Batch:
    """Row-major -> column-major numpy."""
    if not rows:
        return {}
    columns: Dict[str, list] = {k: [] for k in rows[0]}
    for row in rows:
        for key in columns:
            columns[key].append(row[key])
    return {k: np.asarray(v) for k, v in columns.items()}


def batch_to_rows(batch: Any) -> Block:
    """Column-major (dict of arrays/lists) -> rows. Lists of rows pass
    through; scalars broadcast is not supported (match lengths).
    pyarrow Tables and pandas DataFrames returned by a map_batches UDF
    convert too, so `batch_format="pyarrow"/"pandas"` round-trips."""
    if _pa is not None and isinstance(batch, _pa.Table):
        return batch.to_pylist()
    if _pd is not None and isinstance(batch, _pd.DataFrame):
        return batch.to_dict("records")
    if isinstance(batch, list):
        return batch
    if not isinstance(batch, dict):
        raise TypeError(
            f"map_batches must return a dict of columns or a list of "
            f"rows, got {type(batch).__name__}"
        )
    if not batch:
        return []
    lengths = {k: len(v) for k, v in batch.items()}
    n = next(iter(lengths.values()))
    if any(v != n for v in lengths.values()):
        raise ValueError(f"ragged batch columns: {lengths}")
    keys = list(batch.keys())
    return [
        {k: _unwrap(batch[k][i]) for k in keys} for i in range(n)
    ]


def _unwrap(value):
    """numpy scalars -> python scalars for row ergonomics."""
    if isinstance(value, np.generic):
        return value.item()
    return value


def format_batch(rows: Block, batch_format: str):
    if batch_format in ("numpy", "default"):
        return rows_to_batch(rows)
    if batch_format in ("rows", "dicts"):
        return list(rows)
    if batch_format == "pandas":
        import pandas as pd

        return pd.DataFrame(rows)
    if batch_format == "pyarrow":  # reference's canonical block format
        import pyarrow as pa

        return pa.Table.from_pylist(rows)
    raise ValueError(f"unknown batch_format {batch_format!r}")


def iter_slices(rows: Block, size: int) -> Iterable[Block]:
    for start in range(0, len(rows), size):
        yield rows[start : start + size]
