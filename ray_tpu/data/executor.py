"""Streaming execution of Dataset plans.

Reference: python/ray/data/_internal/execution/streaming_executor.py:48
— a pull-based loop moves blocks through operator stages with bounded
in-flight work (backpressure_policy/). Here each map stage is a window
of remote tasks over block refs: up to `window` tasks are in flight per
stage, later stages consume earlier stages' outputs as they are
submitted, and all-to-all stages (shuffle/sort/repartition) are
barriers that materialize their input ref list.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional

import ray_tpu as rt

# One remote hop applies a serialized block transform; num_cpus=1 is
# the reference's default per-map-task resource.
_map_task = None


def _get_map_task():
    global _map_task
    if _map_task is None:

        def apply_block_fn(fn, *blocks):
            return fn(*blocks)

        _map_task = rt.remote(num_cpus=1)(apply_block_fn)
    return _map_task


class Stage:
    name: str = "stage"


class ReadStage(Stage):
    """Source: a list of argless callables, each producing one block."""

    def __init__(self, tasks: List[Callable[[], Any]], name="read"):
        self.tasks = tasks
        self.name = name


class MapStage(Stage):
    """block -> block transform, one remote task per block."""

    def __init__(self, fn: Callable, name="map"):
        self.fn = fn
        self.name = name


class AllToAllStage(Stage):
    """Barrier: fn(list_of_refs) -> list_of_refs (it may submit its own
    remote tasks, e.g. shuffle partition/combine rounds)."""

    def __init__(self, fn: Callable[[List[Any]], List[Any]], name="a2a"):
        self.fn = fn
        self.name = name


class LimitStage(Stage):
    def __init__(self, n: int):
        self.n = n
        self.name = f"limit({n})"


def execute_streaming(
    stages: List[Stage], window: int = 8
) -> Iterator[Any]:
    """Yield output block refs, submitting work stage-by-stage with a
    bounded per-stage window."""
    gen: Iterator[Any] = iter(())
    for stage in stages:
        if isinstance(stage, ReadStage):
            gen = _read_gen(stage, window)
        elif isinstance(stage, MapStage):
            gen = _map_gen(gen, stage, window)
        elif isinstance(stage, AllToAllStage):
            gen = iter(stage.fn(list(gen)))
        elif isinstance(stage, LimitStage):
            gen = _limit_gen(gen, stage.n)
        else:
            raise TypeError(f"unknown stage {stage!r}")
    return gen


def _read_gen(stage: ReadStage, window: int) -> Iterator[Any]:
    task = _get_map_task()
    pending: List[Any] = []
    for read_fn in stage.tasks:
        pending.append(task.remote(read_fn))
        if len(pending) >= window:
            yield pending.pop(0)
    while pending:
        yield pending.pop(0)


def _map_gen(
    upstream: Iterator[Any], stage: MapStage, window: int
) -> Iterator[Any]:
    task = _get_map_task()
    pending: List[Any] = []
    for ref in upstream:
        pending.append(task.remote(stage.fn, ref))
        if len(pending) >= window:
            yield pending.pop(0)
    while pending:
        yield pending.pop(0)


def _limit_gen(upstream: Iterator[Any], n: int) -> Iterator[Any]:
    remaining = n
    for ref in upstream:
        if remaining <= 0:
            return
        block = rt.get(ref)
        if len(block) >= remaining:
            yield rt.put(block[:remaining])
            return
        remaining -= len(block)
        yield ref
