"""Streaming execution of Dataset plans.

Reference: python/ray/data/_internal/execution/streaming_executor.py:48
— a pull-based loop moves blocks through operator stages with bounded
in-flight work. Two backpressure dimensions, matching the reference's
backpressure_policy/ package:

- task-count cap per stage (ConcurrencyCapBackpressurePolicy): at most
  `window` tasks in flight;
- in-flight BYTES budget (the resource-based output backpressure):
  completed-but-unconsumed block bytes per stage are bounded by
  `inflight_bytes`, so a skewed stage whose blocks balloon (flat_map
  fan-out) throttles submission instead of flooding the object store.
  Sizes come from the store's sealed-object metadata; inline (small)
  results are counted by their actual payload bytes.

Stages: ReadStage / MapStage run one task per block; ActorPoolStage
(reference: operators/actor_pool_map_operator.py) runs blocks on an
autoscaling pool of warm actors — the compute model for UDFs with
expensive setup (a loaded model); AllToAllStage is a materializing
barrier; LimitStage truncates.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Iterator, List, Optional

import ray_tpu as rt

#: Default per-stage in-flight bytes budget. Deliberately a fraction of
#: the default object store so two busy stages + consumer still fit.
DEFAULT_INFLIGHT_BYTES = int(
    os.environ.get("RT_DATA_INFLIGHT_BYTES", str(256 * 1024 * 1024))
)

# One remote hop applies a serialized block transform; num_cpus=1 is
# the reference's default per-map-task resource.
_map_task = None


def _get_map_task():
    global _map_task
    if _map_task is None:

        def apply_block_fn(fn, *blocks):
            return fn(*blocks)

        _map_task = rt.remote(num_cpus=1)(apply_block_fn)
    return _map_task


class Stage:
    name: str = "stage"


class ReadStage(Stage):
    """Source: a list of argless callables, each producing one block."""

    def __init__(self, tasks: List[Callable[[], Any]], name="read"):
        self.tasks = tasks
        self.name = name


class MapStage(Stage):
    """block -> block transform, one remote task per block."""

    def __init__(self, fn: Callable, name="map"):
        self.fn = fn
        self.name = name


class ActorPoolStage(Stage):
    """block -> block transform on a pool of warm actors (reference:
    actor_pool_map_operator.py + ActorPoolStrategy). `udf` may be a
    callable CLASS — each pool actor instantiates it once (model load,
    connection setup) and reuses it for every block."""

    def __init__(
        self,
        udf: Any,
        make_apply: Callable,
        *,
        ctor_args: tuple = (),
        min_size: int = 1,
        max_size: int = 4,
        max_tasks_per_actor: int = 2,
        num_cpus: float = 1.0,
        name="map(actors)",
    ):
        self.udf = udf
        self.make_apply = make_apply
        self.ctor_args = ctor_args
        self.min_size = max(1, min_size)
        self.max_size = max(self.min_size, max_size)
        self.max_tasks_per_actor = max(1, max_tasks_per_actor)
        self.num_cpus = num_cpus
        self.name = name


class AllToAllStage(Stage):
    """Barrier: fn(list_of_refs) -> list_of_refs (it may submit its own
    remote tasks, e.g. shuffle partition/combine rounds)."""

    def __init__(self, fn: Callable[[List[Any]], List[Any]], name="a2a"):
        self.fn = fn
        self.name = name


class LimitStage(Stage):
    def __init__(self, n: int):
        self.n = n
        self.name = f"limit({n})"


def execute_streaming(
    stages: List[Stage],
    window: int = 8,
    inflight_bytes: Optional[int] = None,
) -> Iterator[Any]:
    """Yield output block refs, submitting work stage-by-stage with
    bounded per-stage in-flight tasks AND bytes."""
    budget = (
        inflight_bytes if inflight_bytes else DEFAULT_INFLIGHT_BYTES
    )

    def _map_pairs(fn, upstream):
        # A dedicated scope: a bare genexp here would close over the
        # LOOP variable and apply the last stage's fn to every stage.
        return ((fn, ref) for ref in upstream)

    gen: Iterator[Any] = iter(())
    for stage in stages:
        if isinstance(stage, ReadStage):
            gen = _task_gen(
                (
                    (read_fn,)
                    for read_fn in stage.tasks
                ),
                window,
                budget,
            )
        elif isinstance(stage, MapStage):
            gen = _task_gen(
                _map_pairs(stage.fn, gen), window, budget
            )
        elif isinstance(stage, ActorPoolStage):
            gen = _actor_pool_gen(gen, stage, window, budget)
        elif isinstance(stage, AllToAllStage):
            gen = iter(stage.fn(list(gen)))
        elif isinstance(stage, LimitStage):
            gen = _limit_gen(gen, stage.n)
        else:
            raise TypeError(f"unknown stage {stage!r}")
    return gen


class _ByteLedger:
    """Tracks in-flight output bytes for one stage.

    A submitted task's output is unknown until it completes; completed
    blocks report their sealed size (or inline payload bytes). The
    estimate for still-running tasks is the running average of observed
    block sizes, so a stage that starts producing huge blocks throttles
    within one window (reference: resource-based backpressure sizes
    operator outputs from block metadata the same way)."""

    _INLINE_FALLBACK = 32 * 1024

    def __init__(self):
        self._known: dict = {}  # id bytes -> size
        self._avg: float = float(self._INLINE_FALLBACK)
        self.observed = 0

    def _probe(self, ref) -> int:
        from .._private.worker import global_worker

        worker = global_worker()
        oid = ref.id()
        # Inline (direct-transport) results never touch the store;
        # their exact payload bytes live on the submitter-side future.
        direct = getattr(worker, "_direct", None)
        if direct is not None:
            entry = direct.lookup(oid)
            fut = entry[0] if isinstance(entry, tuple) else entry
            if (
                fut is not None
                and fut.done()
                and not fut.daemon_fallback
                and fut.results
            ):
                total = 0
                for kind, payload in fut.results:
                    if kind == "shm":
                        # Sealed-to-store result: payload is its size.
                        total += int(payload)
                    elif payload is not None:
                        total += len(payload)
                return total or self._INLINE_FALLBACK
        try:
            meta = worker.call("get_object_meta", oid=oid.binary())
            size = meta.get("size")
            return int(size) if size else self._INLINE_FALLBACK
        except Exception:
            return self._INLINE_FALLBACK

    def account(self, pending: List[Any]) -> float:
        """Estimated bytes held by `pending` (submitted, not yet
        yielded downstream)."""
        if not pending:
            return 0.0
        ready, _ = rt.wait(
            list(pending), num_returns=len(pending), timeout=0
        )
        for ref in ready:
            key = ref.id().binary()
            if key not in self._known:
                size = self._probe(ref)
                self._known[key] = size
                self.observed += 1
                self._avg += (size - self._avg) / self.observed
        total = 0.0
        ready_keys = {r.id().binary() for r in ready}
        for ref in pending:
            key = ref.id().binary()
            if key in ready_keys:
                total += self._known.get(key, self._avg)
            else:
                total += self._avg
        return total

    def forget(self, ref) -> None:
        self._known.pop(ref.id().binary(), None)


def _task_gen(
    submissions: Iterator[tuple], window: int, budget: int
) -> Iterator[Any]:
    """Common bounded-submission loop for read and map stages: submit
    while under both the task window and the byte budget, otherwise
    hand the oldest block downstream (the pull that frees budget)."""
    task = _get_map_task()
    ledger = _ByteLedger()
    pending: List[Any] = []
    for args in submissions:
        pending.append(task.remote(*args))
        while True:
            est = ledger.account(pending)  # also records sizes
            if not (
                len(pending) >= window
                # Cold-start calibration: until one real output size
                # is observed, the running-average estimate is a tiny
                # prior that would let a whole window of (possibly
                # huge) blocks through — hold at 2 in flight until the
                # first block reports its size.
                or (ledger.observed == 0 and len(pending) >= 2)
                or (len(pending) > 1 and est >= budget)
            ):
                break
            ref = pending.pop(0)
            ledger.forget(ref)
            yield ref
    while pending:
        ref = pending.pop(0)
        ledger.forget(ref)
        yield ref


class _PoolWorker:
    """One warm actor of an ActorPoolStage. The UDF class is
    instantiated HERE, once, so per-actor state (a loaded model)
    amortizes across every block this actor maps (reference:
    actor_pool_map_operator.py _MapWorker)."""

    def __init__(self, udf, make_apply, ctor_args=()):
        instance = udf(*ctor_args) if isinstance(udf, type) else udf
        self._apply = make_apply(instance)

    def apply(self, block):
        return self._apply(block)

    def ping(self):
        return "ok"


def _actor_pool_gen(
    upstream: Iterator[Any],
    stage: ActorPoolStage,
    window: int,
    budget: int,
) -> Iterator[Any]:
    """Autoscaling actor-pool map: blocks dispatch to the least-loaded
    live actor; when every actor is saturated (max_tasks_per_actor)
    and the pool is under max_size, a new actor spins up (reference:
    _ActorPool.scale_up on queued work). Actors are killed when the
    stage drains — including on early downstream termination (limit)."""
    worker_cls = rt.remote(num_cpus=stage.num_cpus)(_PoolWorker)
    ledger = _ByteLedger()
    pool: List[Any] = []
    load: dict = {}  # actor index -> in-flight count
    pending: List[tuple] = []  # (out_ref, actor_idx)

    def spawn():
        actor = worker_cls.remote(
            stage.udf, stage.make_apply, stage.ctor_args
        )
        pool.append(actor)
        load[len(pool) - 1] = 0
        return len(pool) - 1

    try:
        for _ in range(stage.min_size):
            spawn()
        for in_ref in upstream:
            # Pick the least-loaded actor; scale up if all saturated.
            idx = min(range(len(pool)), key=lambda i: load[i])
            if (
                load[idx] >= stage.max_tasks_per_actor
                and len(pool) < stage.max_size
            ):
                idx = spawn()
            out = pool[idx].apply.remote(in_ref)
            load[idx] += 1
            pending.append((out, idx))
            while len(pending) >= window or (
                len(pending) > 1
                and ledger.account([r for r, _ in pending]) >= budget
            ):
                ref, ref_idx = pending.pop(0)
                # Block completion is what frees the actor slot; the
                # oldest submission is (FIFO per actor) the first done.
                rt.wait([ref], num_returns=1)
                load[ref_idx] = max(0, load[ref_idx] - 1)
                ledger.forget(ref)
                yield ref
        while pending:
            ref, ref_idx = pending.pop(0)
            rt.wait([ref], num_returns=1)
            load[ref_idx] = max(0, load[ref_idx] - 1)
            ledger.forget(ref)
            yield ref
    finally:
        # Drain before teardown so in-flight results seal, then
        # release the workers (pool actors are stage-scoped).
        for ref, _ in pending:
            try:
                rt.wait([ref], num_returns=1, timeout=30)
            except Exception:
                pass
        for actor in pool:
            try:
                rt.kill(actor)
            except Exception:
                pass


def _limit_gen(upstream: Iterator[Any], n: int) -> Iterator[Any]:
    remaining = n
    for ref in upstream:
        if remaining <= 0:
            return
        block = rt.get(ref)
        if len(block) >= remaining:
            yield rt.put(block[:remaining])
            return
        remaining -= len(block)
        yield ref
