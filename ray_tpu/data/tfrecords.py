"""TFRecord container + tf.train.Example codec, dependency-free.

Reference: python/ray/data/_internal/datasource/tfrecords_datasource.py
— the reference reads TFRecords through tensorflow. TensorFlow isn't
in this environment (and pulling it in for a framing format would be
absurd on a TPU host that runs JAX), so both layers are implemented
directly:

- container framing: every record is
    uint64le length | uint32le masked-crc32c(length bytes)
    | payload | uint32le masked-crc32c(payload)
  with CRC32C (Castagnoli) and TF's rotate-and-offset masking.
- payload codec: the tf.train.Example proto subset — Features =
  map<string, Feature>, Feature = one of BytesList / FloatList /
  Int64List — parsed/emitted with a ~50-line protobuf wire walker
  (varint + length-delimited fields; packed and unpacked scalars).

Both directions round-trip with real TF output; CRCs are verified on
read (corrupt files fail loudly, matching TF's DataLossError).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

# ---------------------------------------------------------------------------
# CRC32C (software, table-driven) + TF masking
# ---------------------------------------------------------------------------

_CRC_TABLE: List[int] = []


def _build_table() -> None:
    poly = 0x82F63B78  # reversed Castagnoli polynomial
    for n in range(256):
        crc = n
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_build_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for byte in data:
        crc = _CRC_TABLE[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# container framing
# ---------------------------------------------------------------------------

def read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:12])
            if _masked_crc(header[:8]) != len_crc:
                raise ValueError(f"{path}: corrupt length crc")
            payload = f.read(length)
            footer = f.read(4)
            if len(payload) < length or len(footer) < 4:
                raise ValueError(f"{path}: truncated record")
            (data_crc,) = struct.unpack("<I", footer)
            if _masked_crc(payload) != data_crc:
                raise ValueError(f"{path}: corrupt data crc")
            yield payload


def write_records(path: str, payloads) -> None:
    with open(path, "wb") as f:
        for payload in payloads:
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))


# ---------------------------------------------------------------------------
# protobuf wire helpers
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7


def _write_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _fields(buf: bytes) -> Iterator[tuple]:
    """(field_number, wire_type, value) triples; value is int for
    varint/fixed, bytes for length-delimited."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 2:  # length-delimited
            length, pos = _read_varint(buf, pos)
            value = buf[pos : pos + length]
            pos += length
        elif wire == 5:  # fixed32
            value = buf[pos : pos + 4]
            pos += 4
        elif wire == 1:  # fixed64
            value = buf[pos : pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


def _ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field."""
    return _write_varint(field << 3 | 2) + _write_varint(
        len(payload)
    ) + payload


# ---------------------------------------------------------------------------
# tf.train.Example
# ---------------------------------------------------------------------------

def _decode_feature(buf: bytes) -> Any:
    for field, wire, value in _fields(buf):
        if field == 1:  # BytesList
            return [
                v for f, w, v in _fields(value) if f == 1
            ]
        if field == 2:  # FloatList
            floats: List[float] = []
            for f, w, v in _fields(value):
                if f != 1:
                    continue
                if w == 2:  # packed
                    floats.extend(
                        struct.unpack(f"<{len(v) // 4}f", v)
                    )
                else:  # unpacked fixed32
                    floats.append(struct.unpack("<f", v)[0])
            return floats
        if field == 3:  # Int64List
            ints: List[int] = []
            for f, w, v in _fields(value):
                if f != 1:
                    continue
                if w == 2:  # packed varints
                    pos = 0
                    while pos < len(v):
                        n, pos = _read_varint(v, pos)
                        ints.append(_signed64(n))
                else:
                    ints.append(_signed64(v))
            return ints
    return []


def _signed64(n: int) -> int:
    return n - (1 << 64) if n >= 1 << 63 else n


def decode_example(payload: bytes) -> Dict[str, Any]:
    """tf.train.Example bytes -> {feature: scalar or list}. Singleton
    lists unwrap (the common one-value-per-feature case); bytes values
    decode to str when they are valid UTF-8."""
    row: Dict[str, Any] = {}
    for field, _, value in _fields(payload):
        if field != 1:  # Example.features
            continue
        for f2, _, entry in _fields(value):
            if f2 != 1:  # Features.feature map entry
                continue
            key, feature = None, []
            for f3, _, v3 in _fields(entry):
                if f3 == 1:
                    key = v3.decode("utf-8")
                elif f3 == 2:
                    feature = _decode_feature(v3)
            if key is None:
                continue
            values = [
                v.decode("utf-8", "surrogateescape")
                if isinstance(v, bytes) and _is_text(v)
                else v
                for v in feature
            ]
            row[key] = values[0] if len(values) == 1 else values
    return row


def _is_text(raw: bytes) -> bool:
    try:
        raw.decode("utf-8")
        return True
    except UnicodeDecodeError:
        return False


def _encode_feature(values: Any) -> bytes:
    import numpy as np

    if isinstance(values, np.ndarray):
        # Array columns are the common TPU input-pipeline case;
        # features are 1-D lists, so flatten (shape restored by the
        # consumer's reshape, as with TF's own FixedLenFeature).
        kind = values.dtype.kind
        flat = values.reshape(-1)
        if kind in "iub":
            values = [int(v) for v in flat]
        elif kind == "f":
            values = [float(v) for v in flat]
        elif kind in "SU":
            values = list(flat)
        else:
            raise TypeError(
                f"cannot encode ndarray feature of dtype "
                f"{values.dtype}"
            )
    if not isinstance(values, (list, tuple)):
        values = [values]
    if not values:
        return b""
    first = values[0]
    if isinstance(first, (bytes, str)):
        items = b"".join(
            _ld(1, v.encode() if isinstance(v, str) else v)
            for v in values
        )
        return _ld(1, items)  # bytes_list
    if isinstance(first, (np.floating, float)):
        packed = struct.pack(
            f"<{len(values)}f", *(float(v) for v in values)
        )
        return _ld(2, _ld(1, packed))  # float_list, packed
    if isinstance(first, (np.integer, np.bool_, int, bool)):
        packed = b"".join(
            _write_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
            for v in values
        )
        return _ld(3, _ld(1, packed))  # int64_list, packed
    raise TypeError(
        f"cannot encode feature of {type(first).__name__}"
    )


def encode_example(row: Dict[str, Any]) -> bytes:
    entries = b""
    for key, values in row.items():
        entry = _ld(1, key.encode("utf-8")) + _ld(
            2, _encode_feature(values)
        )
        entries += _ld(1, entry)
    return _ld(1, entries)  # Example.features
