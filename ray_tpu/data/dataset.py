"""Dataset: lazy logical plan over row blocks.

Reference: python/ray/data/dataset.py — a Dataset is a lazy plan
(logical operators) executed by the streaming executor into object-
store blocks; map/filter/flat_map/map_batches are per-block tasks,
repartition/random_shuffle/sort/groupby are all-to-all shuffles
(_internal/planner/exchange/), iteration pulls blocks; streaming_split
(dataset.py streaming_split + _internal/execution/operators/
output_splitter.py) feeds Train workers disjoint streams.
"""

from __future__ import annotations

import builtins
import queue as _queue
import random
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu as rt

from .._private import step_telemetry as _telemetry
from .block import (
    Block,
    batch_to_rows,
    format_batch,
    iter_slices,
    rows_to_batch,
)
from .executor import (
    ActorPoolStage,
    AllToAllStage,
    LimitStage,
    MapStage,
    ReadStage,
    Stage,
    execute_streaming,
)


class ActorPoolStrategy:
    """Compute strategy running a stage's UDF on warm, reusable actors
    (reference: python/ray/data/_internal/compute.py ActorPoolStrategy
    + operators/actor_pool_map_operator.py). Use for UDFs with
    expensive per-process setup — the pool autoscales between min_size
    and max_size on backlog."""

    def __init__(
        self,
        min_size: int = 1,
        max_size: int = 4,
        *,
        max_tasks_per_actor: int = 2,
        num_cpus: float = 1.0,
    ):
        if min_size < 1 or max_size < min_size:
            raise ValueError(
                f"bad pool bounds [{min_size}, {max_size}]"
            )
        self.min_size = min_size
        self.max_size = max_size
        self.max_tasks_per_actor = max_tasks_per_actor
        self.num_cpus = num_cpus


#: How far the block-ref stream pulls ahead of a prefetching batch
#: iterator: keeps the streaming executor submitting upstream tasks
#: while the prefetch thread is blocked inside an rt.get.
_REF_PULL_AHEAD = 2


def _prefetched(iterator: Iterator[Any], window: int) -> Iterator[Any]:
    """Run `iterator` on a background thread, yielding its items in
    order through a bounded queue (reference: iter_batches
    prefetch_batches -> _internal/block_batching prefetcher).

    Contract: order-preserving; exceptions from the producer re-raise
    at the consumer's next(); closing the returned generator (early
    `break`, GC) stops the producer promptly, closes the wrapped
    iterator (cascading cancellation for nested prefetchers), and
    joins the thread — no leaked threads, no dangling gets beyond the
    one already in flight.
    """
    out: _queue.Queue = _queue.Queue(maxsize=max(1, window))
    stop = threading.Event()

    def _put(item) -> bool:
        """Blocking put that aborts when the consumer went away."""
        while not stop.is_set():
            try:
                out.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def producer():
        try:
            try:
                for item in iterator:
                    if not _put(("item", item)) or stop.is_set():
                        return  # consumer gone: do not start another get
                _put(("done", None))
            except BaseException as e:  # noqa: BLE001 — relayed to consumer
                _put(("error", e))
        finally:
            close = getattr(iterator, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    thread = threading.Thread(
        target=producer, daemon=True, name="rt-data-prefetch"
    )

    def consume():
        # Started on first next(): a generator that is created but
        # never consumed must not leave a producer thread behind.
        thread.start()
        try:
            while True:
                kind, value = out.get()
                if kind == "item":
                    yield value
                elif kind == "done":
                    return
                else:
                    raise value
        finally:
            stop.set()
            # No drain needed: a producer blocked in put() re-checks
            # stop every 0.1s and exits WITHOUT consuming another
            # item from the wrapped iterator (draining here would let
            # its put succeed and the loop advance into one more
            # blocking get).
            thread.join(timeout=10.0)

    return consume()


def _batches_from_blocks(
    blocks: Iterator[Block],
    batch_size: int,
    batch_format: str,
    drop_last: bool,
) -> Iterator[Any]:
    """The one batching loop: both the serial and the prefetching
    iter_batches run THIS code, so ordering and drop_last semantics
    cannot drift between them."""
    carry: Block = []
    for block in blocks:
        carry.extend(block)
        while len(carry) >= batch_size:
            yield format_batch(carry[:batch_size], batch_format)
            carry = carry[batch_size:]
    if carry and not drop_last:
        yield format_batch(carry, batch_format)


class Dataset:
    def __init__(
        self,
        stages: List[Stage],
        window: int = 8,
        inflight_bytes: Optional[int] = None,
    ):
        self._stages = stages
        self._window = window
        self._inflight_bytes = inflight_bytes
        self._materialized: Optional[List[Any]] = None  # block refs

    # -- plan building -------------------------------------------------
    def _with(self, stage: Stage) -> "Dataset":
        return Dataset(
            self._stages + [stage], self._window, self._inflight_bytes
        )

    def options(
        self,
        *,
        window: Optional[int] = None,
        inflight_bytes: Optional[int] = None,
    ) -> "Dataset":
        """Execution knobs: per-stage in-flight task window and byte
        budget (reference: ExecutionOptions / DataContext resource
        limits)."""
        return Dataset(
            self._stages,
            window if window is not None else self._window,
            inflight_bytes
            if inflight_bytes is not None
            else self._inflight_bytes,
        )

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        return self._with(
            MapStage(lambda block: [fn(row) for row in block], "map")
        )

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        return self._with(
            MapStage(
                lambda block: [row for row in block if fn(row)], "filter"
            )
        )

    def flat_map(self, fn: Callable[[dict], List[dict]]) -> "Dataset":
        return self._with(
            MapStage(
                lambda block: [
                    out for row in block for out in fn(row)
                ],
                "flat_map",
            )
        )

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional[ActorPoolStrategy] = None,
        fn_constructor_args: tuple = (),
    ) -> "Dataset":
        """Per-batch transform. `fn` may be a callable CLASS when
        `compute=ActorPoolStrategy(...)`: each pool actor instantiates
        it once (with fn_constructor_args) and reuses the instance for
        every batch — the warm-state UDF pattern (reference:
        dataset.py map_batches(compute=ActorPoolStrategy))."""

        def make_apply(udf):
            def apply(block: Block) -> Block:
                out: Block = []
                slices = (
                    iter_slices(block, batch_size)
                    if batch_size
                    else [block]
                )
                for rows in slices:
                    result = udf(format_batch(rows, batch_format))
                    out.extend(batch_to_rows(result))
                return out

            return apply

        if compute is not None:
            return self._with(
                ActorPoolStage(
                    fn,
                    make_apply,
                    ctor_args=tuple(fn_constructor_args),
                    min_size=compute.min_size,
                    max_size=compute.max_size,
                    max_tasks_per_actor=compute.max_tasks_per_actor,
                    num_cpus=compute.num_cpus,
                    name="map_batches(actors)",
                )
            )
        if isinstance(fn, type):
            raise ValueError(
                "class UDFs require compute=ActorPoolStrategy(...)"
            )
        return self._with(MapStage(make_apply(fn), "map_batches"))

    def add_column(self, name: str, fn: Callable[[dict], Any]) -> "Dataset":
        return self.map(lambda row: {**row, name: fn(row)})

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map(
            lambda row: {k: v for k, v in row.items() if k not in cols}
        )

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map(lambda row: {k: row[k] for k in cols})

    def limit(self, n: int) -> "Dataset":
        return self._with(LimitStage(n))

    # -- all-to-all ----------------------------------------------------
    def repartition(self, num_blocks: int) -> "Dataset":
        def split(block: Block, n: int) -> List[Block]:
            size = max(1, -(-len(block) // n)) if block else 1
            parts = [
                block[i * size : (i + 1) * size] for i in range(n)
            ]
            return parts

        return self._with(
            AllToAllStage(
                lambda refs: _shuffle(refs, num_blocks, split, _concat),
                "repartition",
            )
        )

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        def split(block: Block, n: int) -> List[Block]:
            rng = random.Random(
                seed if seed is not None else len(block)
            )
            parts: List[Block] = [[] for _ in range(n)]
            for row in block:
                parts[rng.randrange(n)].append(row)
            return parts

        def combine(*parts: Block) -> Block:
            rows = [row for part in parts for row in part]
            random.Random(seed).shuffle(rows)
            return rows

        def run(refs):
            n = max(1, len(refs))
            return _shuffle(refs, n, split, combine)

        return self._with(AllToAllStage(run, "random_shuffle"))

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        def run(refs):
            n = max(1, len(refs))
            if not refs:
                return refs
            # Sample range boundaries (reference: exchange/sort_task_
            # spec.py samples blocks to pick partition boundaries).
            sample_task = rt.remote(num_cpus=1)(
                lambda block: sorted(row[key] for row in block)
            )
            samples = sorted(
                v
                for chunk in rt.get([sample_task.remote(r) for r in refs])
                for v in chunk
            )
            if not samples:
                return refs  # all blocks empty: nothing to sort
            bounds = [
                samples[
                    min((i + 1) * len(samples) // n, len(samples) - 1)
                ]
                for i in range(n - 1)
            ]

            def split(block: Block, parts_n: int) -> List[Block]:
                parts: List[Block] = [[] for _ in range(parts_n)]
                for row in block:
                    import bisect

                    parts[bisect.bisect_right(bounds, row[key])].append(
                        row
                    )
                return parts

            def combine(*parts: Block) -> Block:
                rows = [row for part in parts for row in part]
                rows.sort(key=lambda r: r[key], reverse=descending)
                return rows

            out = _shuffle(refs, n, split, combine)
            return list(reversed(out)) if descending else out

        return self._with(AllToAllStage(run, "sort"))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, other: "Dataset") -> "Dataset":
        def run(refs):
            return refs + list(
                execute_streaming(
                    other._stages,
                    other._window,
                    other._inflight_bytes,
                )
            )

        return self._with(AllToAllStage(run, "union"))

    # -- execution -----------------------------------------------------
    def _block_refs(self) -> List[Any]:
        if self._materialized is None:
            self._materialized = list(
                execute_streaming(
                    self._stages, self._window, self._inflight_bytes
                )
            )
        return self._materialized

    def iter_block_refs(self, *, prefetch: int = 0) -> Iterator[Any]:
        """Yield output block refs. prefetch>0 pulls up to that many
        refs ahead of the consumer on a background thread, keeping the
        streaming executor submitting upstream tasks while the
        consumer is busy (e.g. blocked in rt.get on an earlier
        block)."""
        if self._materialized is not None:
            # Already-resident refs: a pull-ahead thread over an
            # in-memory list buys nothing.
            return iter(self._materialized)
        base = execute_streaming(
            self._stages, self._window, self._inflight_bytes
        )
        if prefetch > 0:
            return _prefetched(base, prefetch)
        return base

    def materialize(self) -> "Dataset":
        self._block_refs()
        return self

    def num_blocks(self) -> int:
        return len(self._block_refs())

    def iter_rows(self) -> Iterator[dict]:
        for ref in self.iter_block_refs():
            yield from rt.get(ref)

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 0,
    ) -> Iterator[Any]:
        """Formatted batches over the block stream.

        prefetch_batches=k (k>0) moves block resolution (rt.get) and
        batch formatting onto a background thread holding up to k
        finished batches ahead of the consumer, with the block-ref
        stream itself pulled ahead — the training step never waits on
        the input pipeline once the window fills. k=0 is the exact
        serial path. Both paths run the same batching loop, so
        ordering and drop_last semantics are identical by
        construction.
        """
        ref_pull_ahead = _REF_PULL_AHEAD if prefetch_batches > 0 else 0

        def blocks() -> Iterator[Block]:
            for ref in self.iter_block_refs(prefetch=ref_pull_ahead):
                yield rt.get(ref)

        batches = _batches_from_blocks(
            blocks(), batch_size, batch_format, drop_last
        )
        if prefetch_batches > 0:
            batches = _prefetched(batches, prefetch_batches)
        # Outermost boundary: what's timed is the consumer-visible
        # stall per batch (post-prefetch), accumulated as the
        # data_wait_ms step phase (_private/step_telemetry.py).
        return _telemetry.timed_iter(batches, "data_wait_ms")

    def take(self, n: int = 20) -> List[dict]:
        out: List[dict] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[dict]:
        return list(self.iter_rows())

    def count(self) -> int:
        count_task = rt.remote(num_cpus=1)(lambda block: len(block))
        return sum(
            rt.get(
                [count_task.remote(r) for r in self.iter_block_refs()]
            )
        )

    def schema(self) -> Dict[str, str]:
        for ref in self.iter_block_refs():
            block = rt.get(ref)
            if block:
                return {
                    k: type(v).__name__ for k, v in block[0].items()
                }
        return {}

    def to_numpy(self) -> Dict[str, np.ndarray]:
        return rows_to_batch(self.take_all())

    def to_pandas(self):
        """Materialize into one DataFrame (reference:
        Dataset.to_pandas)."""
        import pandas as pd

        return pd.DataFrame(self.take_all())

    def to_arrow(self):
        """Materialize into one pyarrow Table (reference:
        Dataset.to_arrow_refs, collapsed to a local table)."""
        import pyarrow as pa

        return pa.Table.from_pylist(self.take_all())

    def iter_torch_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
        device: Optional[str] = None,
        dtypes=None,
        prefetch_batches: int = 0,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as dicts of torch tensors (reference:
        Dataset.iter_torch_batches). Non-numeric columns pass through
        unconverted."""
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size,
            batch_format="numpy",
            drop_last=drop_last,
            prefetch_batches=prefetch_batches,
        ):
            out: Dict[str, Any] = {}
            for key, column in batch.items():
                try:
                    tensor = torch.as_tensor(column)
                except (TypeError, RuntimeError):
                    out[key] = column
                    continue
                if dtypes is not None:
                    want = (
                        dtypes.get(key)
                        if isinstance(dtypes, dict)
                        else dtypes
                    )
                    if want is not None:
                        tensor = tensor.to(want)
                if device is not None:
                    tensor = tensor.to(device)
                out[key] = tensor
            yield out

    def stats(self) -> str:
        refs = self._block_refs()
        return (
            f"Dataset(blocks={len(refs)}, "
            f"stages={[s.name for s in self._stages]})"
        )

    def __repr__(self):
        return f"Dataset(stages={[s.name for s in self._stages]})"

    # -- split ---------------------------------------------------------
    def split(self, n: int) -> List["Dataset"]:
        """Materializing split into n datasets (reference:
        Dataset.split)."""
        refs = self._block_refs()
        outs: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            outs[i % n].append(ref)
        datasets = []
        for part in outs:
            ds = Dataset([], self._window)
            ds._materialized = part
            datasets.append(ds)
        return datasets

    def streaming_split(
        self, n: int, *, equal: bool = False
    ) -> List["DataIterator"]:
        """n disjoint iterators backed by a coordinator actor pulling
        the stream on demand (reference: Dataset.streaming_split ->
        OutputSplitter); the iterators are picklable and usable from
        Train workers."""
        coordinator_cls = rt.remote(num_cpus=0)(_SplitCoordinator)
        coordinator = coordinator_cls.remote(
            self._stages, self._window, n, equal, self._inflight_bytes
        )
        return [DataIterator(coordinator, i) for i in range(n)]

    # -- writes --------------------------------------------------------
    def write_csv(self, path: str) -> None:
        _write(self, path, "csv")

    def write_json(self, path: str) -> None:
        _write(self, path, "json")

    def write_parquet(self, path: str) -> None:
        _write(self, path, "parquet")

    def write_tfrecords(self, path: str) -> None:
        _write(self, path, "tfrecords")

    def write_numpy(self, path: str, *, column: str = "data") -> None:
        _write(self, path, "npy", column=column)


class GroupedData:
    """(reference: python/ray/data/grouped_data.py)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _aggregate(
        self, init: Any, update: Callable, finalize: Callable, name: str
    ) -> Dataset:
        key = self._key

        def run(refs):
            n = max(1, len(refs))

            def split(block: Block, parts_n: int) -> List[Block]:
                parts: List[Block] = [[] for _ in range(parts_n)]
                for row in block:
                    parts[hash(row[key]) % parts_n].append(row)
                return parts

            def combine(*parts: Block) -> Block:
                state: Dict[Any, Any] = {}
                for part in parts:
                    for row in part:
                        group = row[key]
                        state[group] = update(
                            state.get(group, init), row
                        )
                return [
                    {key: group, name: finalize(acc)}
                    for group, acc in sorted(state.items())
                ]

            return _shuffle(refs, n, split, combine)

        return self._ds._with(AllToAllStage(run, f"groupby.{name}"))

    def count(self) -> Dataset:
        return self._aggregate(
            0, lambda acc, row: acc + 1, lambda acc: acc, "count"
        )

    def sum(self, col: str) -> Dataset:
        return self._aggregate(
            0,
            lambda acc, row: acc + row[col],
            lambda acc: acc,
            f"sum({col})",
        )

    def mean(self, col: str) -> Dataset:
        return self._aggregate(
            (0, 0),
            lambda acc, row: (acc[0] + row[col], acc[1] + 1),
            lambda acc: acc[0] / acc[1] if acc[1] else 0.0,
            f"mean({col})",
        )

    def max(self, col: str) -> Dataset:
        return self._aggregate(
            None,
            lambda acc, row: row[col]
            if acc is None
            else builtins.max(acc, row[col]),
            lambda acc: acc,
            f"max({col})",
        )

    def min(self, col: str) -> Dataset:
        return self._aggregate(
            None,
            lambda acc, row: row[col]
            if acc is None
            else builtins.min(acc, row[col]),
            lambda acc: acc,
            f"min({col})",
        )


class _SplitCoordinator:
    """Actor pulling the stream once, handing blocks to n consumers.
    equal=True enforces strict round-robin; otherwise first-come-first-
    served (reference: output_splitter.py)."""

    def __init__(self, stages, window, n, equal, inflight_bytes=None):
        self._iter = execute_streaming(stages, window, inflight_bytes)
        self._n = n
        self._equal = equal
        self._queues: List[List[Block]] = [[] for _ in range(n)]
        self._rr = 0
        self._exhausted = False

    def next_block(self, idx: int):
        import ray_tpu as rt_inner

        if self._queues[idx]:
            return self._queues[idx].pop(0)
        while not self._exhausted:
            try:
                ref = next(self._iter)
            except StopIteration:
                self._exhausted = True
                break
            block = rt_inner.get(ref)
            if self._equal:
                target = self._rr
                self._rr = (self._rr + 1) % self._n
                if target == idx:
                    return block
                self._queues[target].append(block)
            else:
                return block
        if self._queues[idx]:
            return self._queues[idx].pop(0)
        return None


class DataIterator:
    """Per-consumer view of a streaming split (reference:
    python/ray/data/iterator.py DataIterator)."""

    def __init__(self, coordinator, index: int):
        self._coordinator = coordinator
        self._index = index

    def iter_blocks(self) -> Iterator[Block]:
        while True:
            block = rt.get(
                self._coordinator.next_block.remote(self._index)
            )
            if block is None:
                return
            yield block

    def iter_rows(self) -> Iterator[dict]:
        for block in self.iter_blocks():
            yield from block

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 0,
    ) -> Iterator[Any]:
        """Same prefetch contract as Dataset.iter_batches: k>0 pulls
        coordinator blocks and formats batches on a background thread,
        k=0 is the serial path; identical ordering either way."""
        batches = _batches_from_blocks(
            self.iter_blocks(), batch_size, batch_format, drop_last
        )
        if prefetch_batches > 0:
            batches = _prefetched(batches, prefetch_batches)
        return _telemetry.timed_iter(batches, "data_wait_ms")

    def __reduce__(self):
        return (DataIterator, (self._coordinator, self._index))


# -- shuffle machinery -------------------------------------------------
def _concat(*parts: Block) -> Block:
    return [row for part in parts for row in part]


def _shuffle(
    refs: List[Any],
    n: int,
    split_fn: Callable[[Block, int], List[Block]],
    combine_fn: Callable[..., Block],
) -> List[Any]:
    """Two-round exchange (reference: _internal/planner/exchange/):
    every input block splits into n parts; the i-th output block
    combines the i-th part of every input."""
    if not refs:
        return []
    split_task = rt.remote(num_cpus=1, num_returns=n)(
        lambda block: tuple(split_fn(block, n))
        if n > 1
        else split_fn(block, n)[0]
    )
    parts = [split_task.remote(ref) for ref in refs]
    if n == 1:
        parts = [[p] for p in parts]
    combine_task = rt.remote(num_cpus=1)(combine_fn)
    return [
        combine_task.remote(*[parts[j][i] for j in range(len(refs))])
        for i in range(n)
    ]


def _write(ds: Dataset, path: str, fmt: str, **opts) -> None:
    import os

    os.makedirs(path, exist_ok=True)

    def write_block(block: Block, index: int) -> str:
        file_path = os.path.join(path, f"part-{index:05d}.{fmt}")
        if fmt == "csv":
            import csv

            with open(file_path, "w", newline="") as f:
                if block:
                    writer = csv.DictWriter(
                        f, fieldnames=list(block[0].keys())
                    )
                    writer.writeheader()
                    writer.writerows(block)
        elif fmt == "json":
            import json

            with open(file_path, "w") as f:
                for row in block:
                    f.write(json.dumps(row) + "\n")
        elif fmt == "parquet":
            import pyarrow as pa
            import pyarrow.parquet as pq

            table = pa.Table.from_pylist(block)
            pq.write_table(table, file_path)
        elif fmt == "tfrecords":
            from .tfrecords import encode_example, write_records

            write_records(
                file_path,
                (encode_example(row) for row in block),
            )
        elif fmt == "npy":
            column = opts.get("column", "data")
            np.save(
                file_path,
                np.asarray([row[column] for row in block]),
            )
        return file_path

    write_task = rt.remote(num_cpus=1)(write_block)
    refs = [
        write_task.remote(ref, i)
        for i, ref in enumerate(ds.iter_block_refs())
    ]
    rt.get(refs)
