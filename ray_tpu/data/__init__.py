"""Distributed datasets (reference: python/ray/data)."""

from .block import Batch, Block
from .dataset import (
    ActorPoolStrategy,
    DataIterator,
    Dataset,
    GroupedData,
)
from .read_api import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    from_torch,
    range,  # noqa: A004 — reference API name
    read_binary_files,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
    read_tfrecords,
)

__all__ = [
    "ActorPoolStrategy",
    "Dataset",
    "DataIterator",
    "GroupedData",
    "Block",
    "Batch",
    "range",
    "from_items",
    "from_numpy",
    "from_pandas",
    "from_arrow",
    "from_torch",
    "from_huggingface",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_text",
    "read_binary_files",
    "read_tfrecords",
]
