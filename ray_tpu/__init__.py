"""ray_tpu — a TPU-native distributed computing framework.

Capability surface modeled on Ray (reference: python/ray/__init__.py
export list :175) with a TPU-first architecture: hosts and pod slices
are first-class schedulable resources, XLA/ICI collectives replace
NCCL, and the object store feeds JAX zero-copy.
"""

from . import exceptions
from .api import (
    available_resources,
    cancel,
    cluster_resources,
    diagnose,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    nodes,
    profile_gang,
    put,
    remote,
    shutdown,
    state_summary,
    timeline,
    wait,
)
from .actor import ActorClass, ActorHandle, method
from .object_ref import ObjectRef, ObjectRefGenerator
from .remote_function import RemoteFunction

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "method",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "get_runtime_context",
    "nodes",
    "cluster_resources",
    "available_resources",
    "timeline",
    "state_summary",
    "diagnose",
    "profile_gang",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorClass",
    "ActorHandle",
    "RemoteFunction",
    "exceptions",
    "__version__",
]
