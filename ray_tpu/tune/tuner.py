"""Tuner + trial controller.

Reference: python/ray/tune — Tuner (tune/tuner.py) wraps an experiment;
TuneController (tune/execution/tune_controller.py:68) is the event loop
that launches trial actors, polls their results, and enacts scheduler
decisions; experiment state snapshots enable resume
(tune/execution/experiment_state.py). Trials run the function
trainable on a thread inside an actor and stream results through
tune.report (trainable/function_trainable.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from .schedulers import (
    CONTINUE,
    STOP,
    FIFOScheduler,
    PopulationBasedTraining,
    TrialScheduler,
)
from .search import BasicVariantGenerator
from .session import StopTrial, TrialRuntime, set_active

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class _TrialActor:
    """Runs the trainable on a background thread; the controller polls
    `next_results` (reference: function_trainable's RunnerThread)."""

    def __init__(self):
        self._runtime: Optional[TrialRuntime] = None
        self._thread: Optional[threading.Thread] = None
        self._done: Optional[tuple] = None

    def start(self, fn, config, checkpoint=None):
        self._runtime = TrialRuntime(checkpoint)
        self._done = None

        def run():
            set_active(self._runtime)
            try:
                fn(config)
                status, error = "ok", None
            except StopTrial:
                status, error = "stopped", None
            except BaseException as e:  # noqa: BLE001 — reported back
                status, error = "error", e
            finally:
                set_active(None)
            self._done = (status, error)  # rt: noqa[RT201] — single-producer handoff: the store is GIL-atomic and published via the results-queue sentinel
            self._runtime.results.put({"__done__": status})

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return True

    def next_results(self, timeout=0.2):
        """Drain available results; returns (results, done_status)."""
        assert self._runtime is not None
        results: List[dict] = []
        deadline = time.time() + timeout
        done = None
        while True:
            remaining = deadline - time.time()
            try:
                item = self._runtime.results.get(
                    timeout=max(0.0, remaining)
                )
            except Exception:
                break
            if "__done__" in item:
                done = item["__done__"]
                break
            results.append(item)
            if not self._runtime.results.qsize():
                break
        if done == "error":
            error = self._done[1]
        else:
            error = None
        return {"results": results, "done": done, "error": error}

    def request_stop(self):
        assert self._runtime is not None
        self._runtime.stop_requested.set()
        return True

    def latest_checkpoint(self):
        assert self._runtime is not None
        return self._runtime.latest_checkpoint


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    state: str = PENDING
    last_result: Dict[str, Any] = dataclasses.field(default_factory=dict)
    metrics_history: List[dict] = dataclasses.field(default_factory=list)
    checkpoint: Optional[Dict[str, Any]] = None
    error: Optional[str] = None
    actor: Any = None

    def snapshot(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "state": self.state
            if self.state in (TERMINATED, ERROR)
            else PENDING,
            "last_result": self.last_result,
            "checkpoint": self.checkpoint,
            "error": self.error,
        }


@dataclasses.dataclass
class TuneConfig:
    """(reference: tune/tune_config.py)."""

    metric: str = "score"
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Optional[TrialScheduler] = None
    #: Adaptive searcher (e.g. search.TPESearcher). None = grid/random
    #: via BasicVariantGenerator. Composes with any scheduler (TPE +
    #: ASHA = the BOHB recipe).
    search_alg: Optional[Any] = None
    resources_per_trial: Optional[Dict[str, float]] = None
    seed: Optional[int] = None


class TrialResult:
    def __init__(self, trial: Trial):
        self.config = trial.config
        self.metrics = trial.last_result
        self.metrics_history = trial.metrics_history
        self.checkpoint = trial.checkpoint
        self.error = trial.error
        self.trial_id = trial.trial_id


class ResultGrid:
    def __init__(self, trials: List[Trial]):
        self._results = [TrialResult(t) for t in trials]

    def __len__(self):
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i):
        return self._results[i]

    @property
    def errors(self):
        return [r for r in self._results if r.error]

    def get_best_result(
        self, metric: Optional[str] = None, mode: str = "max"
    ) -> TrialResult:
        scored = [
            r for r in self._results if metric is None or metric in r.metrics
        ]
        if not scored:
            raise ValueError("no trial reported the target metric")
        key = (
            (lambda r: r.metrics[metric]) if metric else (lambda r: 0)
        )
        return (
            max(scored, key=key) if mode == "max" else min(scored, key=key)
        )


class Tuner:
    def __init__(
        self,
        trainable: Callable[[dict], Any],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config=None,  # train.RunConfig (name + storage_path)
    ):
        self._trainable = _as_function_trainable(trainable)
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config
        self._trials: List[Trial] = []

    # -- experiment state ---------------------------------------------
    def _storage_dir(self) -> str:
        if self._run_config is not None and getattr(
            self._run_config, "storage_path", None
        ):
            path = self._run_config.storage_path
        else:
            path = tempfile.mkdtemp(prefix="rt_tune_")
        os.makedirs(path, exist_ok=True)
        return path

    @staticmethod
    def restore(
        path: str,
        trainable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        search_alg=None,
        scheduler=None,
    ) -> "Tuner":
        """Resume an interrupted experiment: finished trials keep their
        results; unfinished ones run again from their last checkpoint
        (reference: Tuner.restore + experiment_state.py). Live objects
        (search_alg, scheduler) and the param_space are not serialized
        — re-pass them here to resume an adaptive search: fit() replays
        every finished trial into the searcher before suggesting the
        remaining num_samples."""
        with open(os.path.join(path, "experiment_state.json")) as f:
            state = json.load(f)
        tuner = Tuner(
            trainable,
            param_space=param_space,
            tune_config=TuneConfig(**state["tune_config"]),
        )
        tuner._tune_config.search_alg = search_alg
        tuner._tune_config.scheduler = scheduler
        tuner._storage_override = path  # type: ignore[attr-defined]
        for snap in state["trials"]:
            tuner._trials.append(Trial(**snap))
        return tuner

    def _save_state(self, path: str) -> None:
        cfg = dataclasses.asdict(self._tune_config)
        cfg.pop("scheduler", None)
        cfg.pop("search_alg", None)  # live object; re-passed on restore
        cfg.pop("resources_per_trial", None)
        state = {
            "tune_config": cfg,
            "trials": [t.snapshot() for t in self._trials],
        }
        tmp = os.path.join(path, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, default=str)
        os.replace(tmp, os.path.join(path, "experiment_state.json"))

    # -- main loop -----------------------------------------------------
    def fit(self) -> ResultGrid:
        import ray_tpu as rt

        cfg = self._tune_config
        storage = getattr(self, "_storage_override", None) or (
            self._storage_dir()
        )
        scheduler = cfg.scheduler or FIFOScheduler()
        searcher = cfg.search_alg
        if searcher is not None:
            searcher.setup(
                self._param_space, cfg.metric, cfg.mode, cfg.seed
            )
            # Resumed experiments replay finished trials into the
            # searcher so its model starts where the run left off.
            for t in self._trials:
                if t.state in (TERMINATED, ERROR):
                    searcher.record(
                        t.config, t.last_result, error=(t.state == ERROR)
                    )
        elif not self._trials:
            generator = BasicVariantGenerator(cfg.seed)
            for config in generator.generate(
                self._param_space, cfg.num_samples
            ):
                self._trials.append(
                    Trial(trial_id=uuid.uuid4().hex[:10], config=config)
                )
        actor_cls = rt.remote(
            **(cfg.resources_per_trial or {"num_cpus": 1})
        )(_TrialActor)

        def launch(trial: Trial, checkpoint=None):
            trial.actor = actor_cls.remote()
            rt.get(
                trial.actor.start.remote(
                    self._trainable,
                    trial.config,
                    checkpoint if checkpoint is not None else trial.checkpoint,
                ),
                timeout=60,
            )
            trial.state = RUNNING

        pending = [t for t in self._trials if t.state == PENDING]
        running: List[Trial] = []
        suggested = len(self._trials)

        def next_trial() -> Optional[Trial]:
            nonlocal suggested
            if pending:
                return pending.pop(0)
            if searcher is not None and suggested < cfg.num_samples:
                suggested += 1
                trial = Trial(
                    trial_id=uuid.uuid4().hex[:10],
                    config=searcher.suggest(),
                )
                self._trials.append(trial)
                return trial
            return None

        def trial_finished(trial: Trial) -> None:
            if searcher is not None:
                searcher.record(
                    trial.config, trial.last_result,
                    error=(trial.state == ERROR),
                )

        try:
            while (
                pending or running
                or (searcher is not None and suggested < cfg.num_samples)
            ):
                while len(running) < cfg.max_concurrent_trials:
                    trial = next_trial()
                    if trial is None:
                        break
                    launch(trial)
                    running.append(trial)
                for trial in list(running):
                    reply = rt.get(
                        trial.actor.next_results.remote(0.15), timeout=60
                    )
                    decision = CONTINUE
                    for result in reply["results"]:
                        has_ckpt = result.pop("__has_checkpoint__", False)
                        trial.last_result = result
                        trial.metrics_history.append(result)
                        if has_ckpt:
                            trial.checkpoint = rt.get(
                                trial.actor.latest_checkpoint.remote(),
                                timeout=60,
                            )
                        decision = scheduler.on_result(trial, result)
                        if decision == STOP:
                            break
                    if decision == STOP:
                        rt.get(
                            trial.actor.request_stop.remote(), timeout=60
                        )
                        trial.checkpoint = rt.get(
                            trial.actor.latest_checkpoint.remote(),
                            timeout=60,
                        )
                        rt.kill(trial.actor)
                        running.remove(trial)
                        exploit = None
                        if isinstance(scheduler, PopulationBasedTraining):
                            exploit = scheduler.pop_exploit(trial.trial_id)
                        if exploit is not None:
                            trial.config = exploit["config"]
                            launch(trial, checkpoint=exploit["checkpoint"])
                            running.append(trial)
                        else:
                            trial.state = TERMINATED
                            trial_finished(trial)
                        self._save_state(storage)
                        continue
                    if reply["done"] is not None:
                        trial.checkpoint = rt.get(
                            trial.actor.latest_checkpoint.remote(),
                            timeout=60,
                        )
                        rt.kill(trial.actor)
                        running.remove(trial)
                        if reply["done"] == "error":
                            trial.state = ERROR
                            trial.error = repr(reply["error"])
                        else:
                            trial.state = TERMINATED
                        trial_finished(trial)
                        self._save_state(storage)
        finally:
            for trial in running:
                try:
                    rt.kill(trial.actor)
                except Exception:
                    pass
            self._save_state(storage)
        return ResultGrid(self._trials)


def _as_function_trainable(trainable) -> Callable[[dict], Any]:
    """Accept a plain function or a JaxTrainer (reference:
    BaseTrainer.fit wraps the trainer as a one-trial Tune trainable,
    base_trainer.py:819)."""
    from ..train.trainer import JaxTrainer

    if isinstance(trainable, JaxTrainer):
        trainer = trainable

        def run_trainer(config: dict):
            from . import session as tune_session

            merged = dict(trainer._train_loop_config or {})
            merged.update(config)
            clone = JaxTrainer(
                trainer._train_loop,
                train_loop_config=merged,
                scaling_config=trainer.scaling_config,
                run_config=trainer.run_config,
                backend=trainer.backend,
                backend_config=trainer.backend_config,
                datasets=trainer.datasets,
            )
            result = clone.fit()
            if result.error is not None:
                raise result.error
            tune_session.report(dict(result.metrics))

        return run_trainer
    if callable(trainable):
        return trainable
    raise TypeError(f"unsupported trainable: {trainable!r}")
