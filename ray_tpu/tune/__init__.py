"""Hyperparameter tuning (reference: python/ray/tune)."""

from .schedulers import (
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from .search import (
    BasicVariantGenerator,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from .session import get_checkpoint, report
from .tuner import ResultGrid, TrialResult, TuneConfig, Tuner

__all__ = [
    "Tuner",
    "TuneConfig",
    "ResultGrid",
    "TrialResult",
    "report",
    "get_checkpoint",
    "uniform",
    "loguniform",
    "randint",
    "choice",
    "grid_search",
    "BasicVariantGenerator",
    "TrialScheduler",
    "FIFOScheduler",
    "AsyncHyperBandScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
]
