"""Trial schedulers.

Reference: python/ray/tune/schedulers/ — FIFOScheduler (trial_scheduler
.py), AsyncHyperBandScheduler/ASHA (async_hyperband.py: rungs at
reduction_factor spacing, cutoff at the top 1/rf quantile per rung),
MedianStoppingRule (median_stopping_rule.py), PopulationBasedTraining
(pbt.py: at perturbation_interval the bottom quantile clones the top
quantile's checkpoint and mutates its config).
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"

_UNSET = object()


class TrialScheduler:
    def on_result(self, trial, result: dict) -> str:
        return CONTINUE

    def on_complete(self, trial, result: dict) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: stop a trial at a rung if its metric falls outside the top
    1/reduction_factor of results recorded at that rung."""

    def __init__(
        self,
        metric: str = "score",
        mode: str = "max",
        grace_period: int = 1,
        reduction_factor: int = 3,
        max_t: int = 100,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # Rungs top-down (reference: _Bracket checks the highest rung
        # first and records a trial at most once per rung).
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rungs.reverse()
        self._rung_records: Dict[int, Dict[str, float]] = defaultdict(dict)

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        value = float(value) if self.mode == "max" else -float(value)
        action = CONTINUE
        for rung in self.rungs:
            recorded = self._rung_records[rung]
            if t >= rung and trial.trial_id not in recorded:
                if recorded:
                    import numpy as np

                    cutoff = float(
                        np.nanpercentile(
                            list(recorded.values()),
                            (1 - 1 / self.rf) * 100,
                        )
                    )
                    if value < cutoff:
                        action = STOP
                recorded[trial.trial_id] = value
                break
        if t >= self.max_t:
            action = STOP
        return action


class MedianStoppingRule(TrialScheduler):
    """Stop when a trial's best result falls below the median of other
    trials' running averages at the same step."""

    def __init__(
        self,
        metric: str = "score",
        mode: str = "max",
        grace_period: int = 1,
        min_samples_required: int = 3,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self._history: Dict[str, List[float]] = defaultdict(list)

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        value = float(value) if self.mode == "max" else -float(value)
        self._history[trial.trial_id].append(value)
        if t <= self.grace:
            return CONTINUE
        others = [
            sum(h) / len(h)
            for tid, h in self._history.items()
            if tid != trial.trial_id and h
        ]
        if len(others) < self.min_samples:
            return CONTINUE
        ordered = sorted(others)
        median = ordered[len(ordered) // 2]
        best = max(self._history[trial.trial_id])
        if best < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: periodically the bottom quantile exploits (clones config +
    checkpoint of) the top quantile, then explores (mutates).

    The controller enacts the decision: `on_result` returns STOP for
    the victim and records an exploit directive the controller reads
    via `pop_exploit` (restart same trial from donor checkpoint with
    mutated config)."""

    def __init__(
        self,
        metric: str = "score",
        mode: str = "max",
        perturbation_interval: int = 4,
        hyperparam_mutations: Optional[
            Dict[str, Callable[[Any], Any] | List[Any]]
        ] = None,
        quantile_fraction: float = 0.25,
        time_attr: str = "training_iteration",
        seed: Optional[int] = None,
    ):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        self._last_score: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = defaultdict(int)
        self._exploits: Dict[str, dict] = {}
        self._trials: Dict[str, Any] = {}

    def _ranked(self) -> List[str]:
        pairs = sorted(
            self._last_score.items(),
            key=lambda kv: kv[1],
            reverse=True,
        )
        return [tid for tid, _ in pairs]

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        self._trials[trial.trial_id] = trial
        self._last_score[trial.trial_id] = (
            float(value) if self.mode == "max" else -float(value)
        )
        if t - self._last_perturb[trial.trial_id] < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = self._ranked()
        if len(ranked) < 2:
            return CONTINUE
        k = max(1, int(len(ranked) * self.quantile))
        bottom = ranked[-k:]
        top = ranked[:k]
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        donor_id = self._rng.choice(top)
        donor = self._trials.get(donor_id)
        if donor is None or donor.checkpoint is None:
            return CONTINUE
        self._exploits[trial.trial_id] = {
            "config": self._explore(dict(donor.config)),
            "checkpoint": donor.checkpoint,
        }
        return STOP

    def _explore(self, config: dict) -> dict:
        for key, mutation in self.mutations.items():
            if isinstance(mutation, list):
                config[key] = self._rng.choice(mutation)
            elif callable(mutation):
                config[key] = mutation(config.get(key))
            else:
                raise TypeError(
                    "hyperparam_mutations values must be lists or "
                    "callables"
                )
        return config

    def pop_exploit(self, trial_id: str) -> Optional[dict]:
        return self._exploits.pop(trial_id, None)
