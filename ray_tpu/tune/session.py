"""In-trial session: tune.report / tune.get_checkpoint.

Reference: ray.tune function-trainable session (python/ray/tune/
trainable/function_trainable.py) — the user function runs on a thread
inside the trial actor; report() hands a result to the controller and
blocks until the controller decides; a stop decision surfaces as
StopTrial at the next report call.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional


class StopTrial(Exception):
    """Raised inside the trainable when the scheduler stops the trial;
    the runner thread exits cleanly."""


class _TrialSession(threading.local):
    def __init__(self):
        self.active: Optional["TrialRuntime"] = None


_session = _TrialSession()


class TrialRuntime:
    """Lives inside the trial actor; bridges the trainable thread and
    the controller's polling."""

    def __init__(self, checkpoint: Optional[Dict[str, Any]] = None):
        # maxsize=1 makes report() block until the controller drains
        # the result — the reference's rendezvous semantics, without
        # which fast trials outrun the scheduler's stop decisions.
        self.results: "queue.Queue[dict]" = queue.Queue(maxsize=1)
        self.stop_requested = threading.Event()
        self.checkpoint_in = checkpoint
        self.latest_checkpoint: Optional[Dict[str, Any]] = checkpoint
        self.iteration = 0

    def report(
        self,
        metrics: Dict[str, Any],
        checkpoint: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.iteration += 1
        out = dict(metrics)
        out.setdefault("training_iteration", self.iteration)
        if checkpoint is not None:
            self.latest_checkpoint = dict(checkpoint)
        out["__has_checkpoint__"] = self.latest_checkpoint is not None
        self.results.put(out)
        if self.stop_requested.is_set():
            raise StopTrial()


def report(
    metrics: Dict[str, Any],
    *,
    checkpoint: Optional[Dict[str, Any]] = None,
) -> None:
    """Report one iteration's metrics (and optionally a checkpoint
    dict) from inside a trainable."""
    if _session.active is None:
        raise RuntimeError(
            "tune.report() called outside a Tune trial"
        )
    _session.active.report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Optional[Dict[str, Any]]:
    """Checkpoint to resume from (set when the trial was restored or
    cloned by PBT), else None."""
    if _session.active is None:
        return None
    return _session.active.checkpoint_in


def set_active(runtime: Optional[TrialRuntime]) -> None:
    _session.active = runtime
