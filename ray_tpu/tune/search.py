"""Search spaces and suggestion generation.

Reference: python/ray/tune/search/ — sample domains
(tune/search/sample.py: uniform/loguniform/randint/choice,
grid_search), and BasicVariantGenerator (basic_variant.py) which
crosses grid axes and samples stochastic axes num_samples times.
"""

from __future__ import annotations

import math
import random
from itertools import product
from typing import Any, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        if low <= 0 or high <= 0:
            raise ValueError("loguniform bounds must be positive")
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(
            rng.uniform(math.log(self.low), math.log(self.high))
        )


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high  # [low, high) like the reference

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories: List[Any]) -> Choice:
    return Choice(categories)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


def _is_grid(value: Any) -> bool:
    return (
        isinstance(value, dict)
        and set(value.keys()) == {"grid_search"}
    )


class BasicVariantGenerator:
    """Cross product of grid axes × num_samples of stochastic axes
    (reference: tune/search/basic_variant.py)."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def generate(
        self, param_space: Dict[str, Any], num_samples: int
    ) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in param_space.items() if _is_grid(v)]
        grid_values = [param_space[k]["grid_search"] for k in grid_keys]
        combos = list(product(*grid_values)) if grid_keys else [()]
        configs = []
        for _ in range(num_samples):
            for combo in combos:
                config: Dict[str, Any] = {}
                for key, value in param_space.items():
                    if key in grid_keys:
                        config[key] = combo[grid_keys.index(key)]
                    elif isinstance(value, Domain):
                        config[key] = value.sample(self._rng)
                    else:
                        config[key] = value
                configs.append(config)
        return configs


# ---------------------------------------------------------------------
# Adaptive searchers (reference slot: tune/search/optuna, hyperopt —
# the suggest/observe Searcher contract of tune/search/searcher.py:34.
# Implemented natively: TPE is the algorithm behind both HyperOpt and
# Optuna's default sampler, so one honest implementation covers the
# role the reference fills with external libraries.)
# ---------------------------------------------------------------------


class Searcher:
    """Sequential suggest/observe protocol (reference:
    tune/search/searcher.py Searcher.suggest/on_trial_complete)."""

    def setup(
        self,
        param_space: Dict[str, Any],
        metric: str,
        mode: str,
        seed: Optional[int] = None,
    ) -> None:
        for key, value in param_space.items():
            if _is_grid(value):
                raise ValueError(
                    f"grid_search axis {key!r} is incompatible with an "
                    "adaptive searcher; use BasicVariantGenerator "
                    "(search_alg=None) for grids"
                )
        self.param_space = param_space
        self.metric = metric
        self.mode = mode
        self.rng = random.Random(seed)

    def suggest(self) -> Dict[str, Any]:
        raise NotImplementedError

    def record(
        self,
        config: Dict[str, Any],
        result: Optional[Dict[str, Any]],
        error: bool = False,
    ) -> None:
        """Observe a finished trial (reference:
        Searcher.on_trial_complete)."""
        raise NotImplementedError


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (Bergstra et al. 2011).

    Observations split at the gamma-quantile into good/bad sets; each
    numeric dimension gets a Parzen mixture (gaussians at observed
    points, bandwidth from the observed spread), categorical dims get
    smoothed frequency tables. Candidates sample from the good model
    and the one maximizing l(x)/g(x) is suggested. LogUniform dims are
    modeled in log space.
    """

    def __init__(
        self,
        n_startup: int = 10,
        gamma: float = 0.15,
        n_candidates: int = 64,
    ):
        self._n_startup = n_startup
        self._gamma = gamma
        self._n_candidates = n_candidates
        self._obs: List[tuple] = []  # (config, objective: higher=better)

    # -- observation ---------------------------------------------------
    def record(self, config: Dict[str, Any], result, error=False):
        if error or not result or self.metric not in result:
            return
        value = float(result[self.metric])
        if self.mode == "min":
            value = -value
        self._obs.append((config, value))

    # -- modeling ------------------------------------------------------
    def _to_unit(self, key: str, value: Any) -> Optional[float]:
        dom = self.param_space[key]
        if isinstance(dom, LogUniform):
            lo, hi = math.log(dom.low), math.log(dom.high)
            return (math.log(value) - lo) / (hi - lo)
        if isinstance(dom, Uniform):
            return (value - dom.low) / (dom.high - dom.low)
        if isinstance(dom, RandInt):
            return (value - dom.low) / max(1, dom.high - 1 - dom.low)
        return None  # categorical

    def _from_unit(self, key: str, u: float) -> Any:
        dom = self.param_space[key]
        u = min(1.0, max(0.0, u))
        if isinstance(dom, LogUniform):
            lo, hi = math.log(dom.low), math.log(dom.high)
            return min(dom.high, max(dom.low, math.exp(lo + u * (hi - lo))))
        if isinstance(dom, Uniform):
            return min(
                dom.high, max(dom.low, dom.low + u * (dom.high - dom.low))
            )
        if isinstance(dom, RandInt):
            return dom.low + round(u * max(0, dom.high - 1 - dom.low))
        raise TypeError(key)

    @staticmethod
    def _bandwidths(points: List[float]) -> List[float]:
        """Per-point bandwidths = distance to the farther neighbor
        (Bergstra 2011 §4: adaptive Parzen estimator) — tight clusters
        refine, isolated points keep exploring."""
        order = sorted(range(len(points)), key=lambda i: points[i])
        bws = [0.0] * len(points)
        for pos, i in enumerate(order):
            left = points[order[pos - 1]] if pos > 0 else 0.0
            right = (
                points[order[pos + 1]] if pos + 1 < len(order) else 1.0
            )
            bws[i] = min(
                1.0, max(points[i] - left, right - points[i], 0.01)
            )
        return bws

    @staticmethod
    def _parzen_logpdf(
        points: List[float], bws: List[float], x: float
    ) -> float:
        """Mixture of per-point gaussians + a uniform prior component
        (weight 1/(n+1)), matching l(x)/g(x) of the paper."""
        if not points:
            return 0.0
        total = 1.0  # uniform prior: pdf 1 on the unit interval
        for p, bw in zip(points, bws):
            z = (x - p) / bw
            total += math.exp(-0.5 * z * z) / (bw * 2.5066282746310002)
        return math.log(total / (len(points) + 1) + 1e-12)

    def suggest(self) -> Dict[str, Any]:
        sampled = {
            k: (v.sample(self.rng) if isinstance(v, Domain) else v)
            for k, v in self.param_space.items()
        }
        if len(self._obs) < self._n_startup:
            return sampled
        # Deduplicate before modeling: repeated suggestions of the
        # same point otherwise flood the elite set with clones, the
        # spread collapses, and the model freezes on a mediocre
        # optimum (premature convergence).
        seen = set()
        distinct = []
        for cfg, val in sorted(self._obs, key=lambda o: -o[1]):
            key = tuple(
                round(v, 6) if isinstance(v, float) else v
                for v in (cfg[k] for k in sorted(cfg))
            )
            if key not in seen:
                seen.add(key)
                distinct.append((cfg, val))
        ranked = distinct
        # Optuna-style tightening: the good set grows sublinearly and
        # caps, so late-stage models sharpen around the elite instead
        # of dragging early random points along forever.
        n_good = max(2, min(25, int(math.ceil(self._gamma * len(ranked)))))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        # Per-dimension models depend only on the good/bad split —
        # build once, then score all candidates against them.
        models: Dict[str, tuple] = {}
        for key, dom in self.param_space.items():
            if not isinstance(dom, Domain):
                continue
            if isinstance(dom, Choice):
                counts_g = {c: 1.0 for c in dom.categories}
                counts_b = {c: 1.0 for c in dom.categories}
                for g in good:
                    counts_g[g[key]] = counts_g.get(g[key], 1.0) + 1
                for b in bad:
                    counts_b[b[key]] = counts_b.get(b[key], 1.0) + 1
                models[key] = (
                    "choice",
                    counts_g, sum(counts_g.values()),
                    counts_b, sum(counts_b.values()),
                )
            else:
                g_pts = [self._to_unit(key, g[key]) for g in good]
                b_pts = [self._to_unit(key, b[key]) for b in bad]
                models[key] = (
                    "num",
                    g_pts, self._bandwidths(g_pts),
                    b_pts, self._bandwidths(b_pts),
                )
        best, best_score = sampled, -math.inf
        for _ in range(self._n_candidates):
            cand: Dict[str, Any] = {}
            score = 0.0
            for key, dom in self.param_space.items():
                if not isinstance(dom, Domain):
                    cand[key] = dom
                    continue
                model = models[key]
                if model[0] == "choice":
                    _, counts_g, total_g, counts_b, total_b = model
                    cats = list(counts_g)
                    pick = self.rng.choices(
                        cats, weights=[counts_g[c] for c in cats]
                    )[0]
                    cand[key] = pick
                    score += math.log(counts_g[pick] / total_g)
                    score -= math.log(counts_b[pick] / total_b)
                    continue
                _, g_pts, g_bws, b_pts, b_bws = model
                # Sample from l(x): the per-point-bandwidth mixture
                # plus its uniform prior component.
                if self.rng.random() < 1.0 / (len(g_pts) + 1):
                    u = self.rng.random()
                else:
                    i = self.rng.randrange(len(g_pts))
                    u = self.rng.gauss(g_pts[i], g_bws[i])
                u = min(1.0, max(0.0, u))
                cand[key] = self._from_unit(key, u)
                score += self._parzen_logpdf(g_pts, g_bws, u)
                score -= self._parzen_logpdf(b_pts, b_bws, u)
            if score > best_score:
                best, best_score = cand, score
        return best
