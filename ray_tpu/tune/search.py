"""Search spaces and suggestion generation.

Reference: python/ray/tune/search/ — sample domains
(tune/search/sample.py: uniform/loguniform/randint/choice,
grid_search), and BasicVariantGenerator (basic_variant.py) which
crosses grid axes and samples stochastic axes num_samples times.
"""

from __future__ import annotations

import math
import random
from itertools import product
from typing import Any, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        if low <= 0 or high <= 0:
            raise ValueError("loguniform bounds must be positive")
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(
            rng.uniform(math.log(self.low), math.log(self.high))
        )


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high  # [low, high) like the reference

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories: List[Any]) -> Choice:
    return Choice(categories)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


def _is_grid(value: Any) -> bool:
    return (
        isinstance(value, dict)
        and set(value.keys()) == {"grid_search"}
    )


class BasicVariantGenerator:
    """Cross product of grid axes × num_samples of stochastic axes
    (reference: tune/search/basic_variant.py)."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def generate(
        self, param_space: Dict[str, Any], num_samples: int
    ) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in param_space.items() if _is_grid(v)]
        grid_values = [param_space[k]["grid_search"] for k in grid_keys]
        combos = list(product(*grid_values)) if grid_keys else [()]
        configs = []
        for _ in range(num_samples):
            for combo in combos:
                config: Dict[str, Any] = {}
                for key, value in param_space.items():
                    if key in grid_keys:
                        config[key] = combo[grid_keys.index(key)]
                    elif isinstance(value, Domain):
                        config[key] = value.sample(self._rng)
                    else:
                        config[key] = value
                configs.append(config)
        return configs
