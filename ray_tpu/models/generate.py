"""Autoregressive decoding for the Llama family.

The serving-side counterpart of models/llama.py (the reference serves
models through vLLM-on-Ray rather than shipping its own decoder; a
TPU-native framework needs one in-tree). Decode is a two-phase jitted
program, the standard TPU inference shape:

  * prefill — one full forward over the padded prompt writes the KV
    cache (flash attention, MXU-bound);
  * decode  — `lax.scan` over steps, each a single-token forward
    against the cache (HBM-bandwidth-bound), with greedy / temperature
    / top-k sampling under a fixed token budget (static shapes; rows
    that hit EOS keep computing but emit padding — the XLA-friendly
    trade).

The KV cache layout [layers, batch, heads, max_len, head_dim] shards
over tp on heads, so tensor-parallel decode needs no cache reshuffle.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .._private import compile_watch
from ..ops.norms import apply_rotary, rotary_embedding
from .llama import embed_tokens, model_glu, model_norm
from .llama import LlamaConfig, project_qkv


def init_kv_cache(
    cfg: LlamaConfig, batch: int, max_len: int
) -> Dict[str, jax.Array]:
    shape = (
        cfg.n_layers,
        batch,
        cfg.n_kv_heads,
        max_len,
        cfg.head_dim,
    )
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "length": jnp.zeros((batch,), jnp.int32),
    }


def _layer_with_cache(
    cfg: LlamaConfig,
    x: jax.Array,  # [b, t, dim]
    layer: Dict[str, jax.Array],
    cos,
    sin,
    k_cache,  # [b, kv_heads, max_len, hd]
    v_cache,
    cache_pos: jax.Array,  # [b] per-row start offset of x
    valid_len: jax.Array,  # [b] per-row valid length incl. x
):
    b, t, _ = x.shape
    hd = cfg.head_dim
    h = model_norm(cfg, x, layer["attn_norm"])
    q, k, v = project_qkv(cfg, h, layer)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    if cache_pos.ndim:
        # Per-row offsets (the engine's slot batch: rows sit at
        # different sequence positions) — vmapped update lowers to a
        # batched scatter.
        _update = jax.vmap(
            lambda c, n, p: jax.lax.dynamic_update_slice(
                c, n, (0, p, 0)
            )
        )
        k_cache = _update(k_cache, k.astype(k_cache.dtype), cache_pos)
        v_cache = _update(v_cache, v.astype(v_cache.dtype), cache_pos)
    else:
        # Uniform offset (generate's scan decode, whole-prompt
        # prefill): keep the contiguous single dynamic_update_slice —
        # a scatter here would tax the HBM-bound hot path for
        # nothing.
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, cache_pos, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, cache_pos, 0)
        )
    max_len = k_cache.shape[2]
    groups = cfg.n_heads // cfg.n_kv_heads
    kf = jnp.repeat(k_cache, groups, axis=1)
    vf = jnp.repeat(v_cache, groups, axis=1)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = (
        jnp.einsum(
            "bhqd,bhkd->bhqk",
            q.astype(jnp.float32),
            kf.astype(jnp.float32),
        )
        * scale
    )
    # Causal + cache-validity mask over absolute positions; q_pos and
    # valid_len each broadcast from scalar (uniform) or per-row form.
    k_pos = jnp.arange(max_len)
    if cache_pos.ndim:
        q_pos = cache_pos[:, None] + jnp.arange(t)[None, :]  # [b, t]
    else:
        q_pos = (cache_pos + jnp.arange(t))[None, :]  # [1, t]
    vl = (
        valid_len[:, None, None] if valid_len.ndim else valid_len
    )
    mask = (k_pos[None, None, :] <= q_pos[:, :, None]) & (
        k_pos[None, None, :] < vl
    )  # [b or 1, t, max_len]
    logits = jnp.where(mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    attn = jnp.einsum("bhqk,bhkd->bhqd", probs, vf.astype(jnp.float32))
    attn = attn.astype(cfg.dtype).transpose(0, 2, 1, 3).reshape(b, t, -1)
    x = x + attn @ layer["wo"]
    h = model_norm(cfg, x, layer["mlp_norm"])
    x = x + model_glu(cfg, h @ layer["w1"], h @ layer["w3"]) @ layer["w2"]
    return x, k_cache, v_cache


def _forward_with_cache(
    params, cfg: LlamaConfig, tokens, cache, cache_pos, valid_len
):
    """tokens [b, t] -> (logits [b, t, vocab], new cache).

    `cache_pos` / `valid_len` may each (independently) be scalars
    (whole batch at one offset, the `generate` path) or `[b]` arrays
    (per-row offsets/lengths — the engine's slot batch, ragged
    `generate_stream` prefill). Scalars keep the original contiguous
    cache update; per-row offsets take the vmapped scatter."""
    b, t = tokens.shape
    cache_pos = jnp.asarray(cache_pos, jnp.int32)
    valid_len = jnp.asarray(valid_len, jnp.int32)
    row_pos = cache_pos[:, None] if cache_pos.ndim else cache_pos
    positions = row_pos + jnp.broadcast_to(jnp.arange(t), (b, t))
    x = embed_tokens(cfg, params, tokens)
    cos, sin = rotary_embedding(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    def body(carry, inputs):
        x = carry
        layer, k_cache, v_cache = inputs
        x, k_cache, v_cache = _layer_with_cache(
            cfg, x, layer, cos, sin, k_cache, v_cache, cache_pos,
            valid_len,
        )
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = model_norm(cfg, x, params["final_norm"])
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": cache["length"]}


def _sample(logits, key, temperature: float, top_k: int):
    """logits [b, vocab] -> token ids [b]."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        top_vals, _ = jax.lax.top_k(logits, top_k)
        cutoff = top_vals[:, -1][:, None]
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


# ---------------------------------------------------------------------
# Shared decode kernel: `generate` (scan body), `generate_stream` and
# the continuous-batching engine (llm/engine.py) all run THIS step —
# one sampling implementation, one cache-update implementation. The
# jitted wrappers are the per-step dispatch entry points; `generate`
# inlines `_decode_step` inside its own jit/scan.
# ---------------------------------------------------------------------


def _decode_step(
    params,
    cfg: LlamaConfig,
    cache,
    last_logits,  # [b, vocab] logits of each row's last valid token
    positions,  # [] or [b] current per-row sequence length
    alive,  # [b] bool; dead rows feed token 0 (ignored downstream)
    key,
    temperature: float,
    top_k: int,
):
    """Sample one token from `last_logits`, run the single-token
    forward against the cache at `positions`, and return
    (token [b], new cache, next last_logits [b, vocab])."""
    token = _sample(last_logits, key, temperature, top_k)
    token = jnp.where(alive, token, 0)
    logits, cache = _forward_with_cache(
        params, cfg, token[:, None], cache, positions, positions + 1
    )
    return token, cache, logits[:, 0]


def accel_donate(*argnums: int):
    """`donate_argnums` for a per-step serving jit: donate (in-place
    update) on accelerator backends — decode is HBM-bound and the KV
    cache must not be copied per token — but NOT on CPU, where XLA
    donation is broken under forced host devices (same gating as
    bench.py's donate=False CPU fallback, PR 4). Called lazily so
    importing this module never initializes a backend."""
    return () if jax.default_backend() == "cpu" else argnums


_decode_step_jit = None


def decode_step(
    params,
    cfg: LlamaConfig,
    cache,
    last_logits,
    positions,
    alive,
    key,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
):
    """Jitted single-step decode — the per-step dispatch entry point
    shared by `generate_stream` and the engine. Compiles once per
    (batch, cache, sampling) shape; `positions` may be per-row. On
    accelerator backends the passed-in `cache`/`last_logits` buffers
    are DONATED (updated in place): treat them as consumed and use
    the returned values."""
    global _decode_step_jit
    if _decode_step_jit is None:
        _decode_step_jit = compile_watch.instrument(
            "generate.decode_step",
            partial(
                jax.jit,
                static_argnames=("temperature", "top_k", "cfg"),
                donate_argnums=accel_donate(2, 3),
            )(_decode_step),
        )
    return _decode_step_jit(
        params, cfg, cache, last_logits, positions, alive, key,
        temperature=temperature, top_k=top_k,
    )


_prefill_jit = None


def prefill(params, cfg: LlamaConfig, tokens, cache, cache_pos, valid_len):
    """Jitted KV-cache prefill: one forward over `tokens` writing the
    cache at `cache_pos`. Shared by `generate_stream` and the engine's
    chunked prefill (one compile per (chunk, cache) shape bucket).
    `cache` is donated on accelerator backends — use the returned
    cache."""
    global _prefill_jit
    if _prefill_jit is None:
        _prefill_jit = compile_watch.instrument(
            "generate.prefill",
            partial(
                jax.jit,
                static_argnames=("cfg",),
                donate_argnums=accel_donate(3),
            )(_forward_with_cache),
        )
    return _prefill_jit(
        params, cfg, tokens, cache, cache_pos, valid_len
    )


# ---------------------------------------------------------------------
# Paged KV: one shared block pool instead of per-row [max_len] arenas.
# A sequence's cache lives in `block_len`-sized blocks scattered across
# the pool; a per-row BLOCK TABLE maps logical block j -> physical
# block id. Attention gathers each row's blocks back into logical
# order, so the math is identical to the contiguous cache above with
# max_len == n_logical_blocks * block_len — the paged engine stays
# token-for-token equal to `generate()` (llm/kv_slots.py owns the
# allocator/refcounting; this module owns the compute).
# ---------------------------------------------------------------------


def init_block_pool(
    cfg: LlamaConfig, n_blocks: int, block_len: int
) -> Dict[str, jax.Array]:
    """The shared pool: k/v of shape
    [layers, n_blocks, kv_heads, block_len, head_dim]."""
    shape = (
        cfg.n_layers,
        n_blocks,
        cfg.n_kv_heads,
        block_len,
        cfg.head_dim,
    )
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _paged_layer(
    cfg: LlamaConfig,
    x: jax.Array,  # [b, t, dim]
    layer: Dict[str, jax.Array],
    cos,
    sin,
    k_pool,  # [n_blocks, kv_heads, block_len, hd] (one layer's slice)
    v_pool,
    tables: jax.Array,  # [b, n_logical_blocks] physical block ids
    q_pos: jax.Array,  # [b, t] absolute positions of x's tokens
    valid_len: jax.Array,  # [b] valid cache length incl. x
):
    b, t, _ = x.shape
    hd = cfg.head_dim
    bl = k_pool.shape[2]
    nb = tables.shape[1]
    h = model_norm(cfg, x, layer["attn_norm"])
    q, k, v = project_qkv(cfg, h, layer)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    # Scatter this step's k/v: position p of row i lands in physical
    # block tables[i, p // bl] at offset p % bl. Rows never share a
    # writable block (the allocator hands a block to one sequence;
    # dead rows all point at the reserved null block 0, whose junk is
    # never gathered by a live row), so the flattened scatter indices
    # only collide harmlessly on the null block.
    phys = jnp.take_along_axis(tables, q_pos // bl, axis=1)  # [b, t]
    off = q_pos % bl
    flat_phys = phys.reshape(-1)
    flat_off = off.reshape(-1)
    k_rows = k.transpose(0, 2, 1, 3).reshape(b * t, cfg.n_kv_heads, hd)
    v_rows = v.transpose(0, 2, 1, 3).reshape(b * t, cfg.n_kv_heads, hd)
    k_pool = k_pool.at[flat_phys, :, flat_off].set(
        k_rows.astype(k_pool.dtype)
    )
    v_pool = v_pool.at[flat_phys, :, flat_off].set(
        v_rows.astype(v_pool.dtype)
    )
    # Gather each row's cache back into logical order: [b, nb, kvH,
    # bl, hd] -> [b, kvH, nb*bl, hd]. Gather AFTER the scatter so the
    # chunk attends to its own tokens (prefill self-attention).
    kf = k_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
        b, cfg.n_kv_heads, nb * bl, hd
    )
    vf = v_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
        b, cfg.n_kv_heads, nb * bl, hd
    )
    groups = cfg.n_heads // cfg.n_kv_heads
    kf = jnp.repeat(kf, groups, axis=1)
    vf = jnp.repeat(vf, groups, axis=1)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = (
        jnp.einsum(
            "bhqd,bhkd->bhqk",
            q.astype(jnp.float32),
            kf.astype(jnp.float32),
        )
        * scale
    )
    k_pos = jnp.arange(nb * bl)
    mask = (k_pos[None, None, :] <= q_pos[:, :, None]) & (
        k_pos[None, None, :] < valid_len[:, None, None]
    )  # [b, t, nb*bl]
    logits = jnp.where(mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    attn = jnp.einsum("bhqk,bhkd->bhqd", probs, vf.astype(jnp.float32))
    attn = attn.astype(cfg.dtype).transpose(0, 2, 1, 3).reshape(b, t, -1)
    x = x + attn @ layer["wo"]
    h = model_norm(cfg, x, layer["mlp_norm"])
    x = x + model_glu(cfg, h @ layer["w1"], h @ layer["w3"]) @ layer["w2"]
    return x, k_pool, v_pool


def _paged_forward(
    params, cfg: LlamaConfig, tokens, pool, tables, q_pos, valid_len
):
    """tokens [b, t] at absolute positions q_pos [b, t] -> (logits
    [b, t, vocab], new pool). The paged analog of
    `_forward_with_cache`; `tables` maps each row's logical blocks to
    pool blocks and `valid_len` [b] bounds what attention may see."""
    q_pos = jnp.asarray(q_pos, jnp.int32)
    valid_len = jnp.asarray(valid_len, jnp.int32)
    x = embed_tokens(cfg, params, tokens)
    cos, sin = rotary_embedding(
        q_pos, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    def body(carry, inputs):
        x = carry
        layer, k_pool, v_pool = inputs
        x, k_pool, v_pool = _paged_layer(
            cfg, x, layer, cos, sin, k_pool, v_pool, tables, q_pos,
            valid_len,
        )
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], pool["k"], pool["v"])
    )
    x = model_norm(cfg, x, params["final_norm"])
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def _paged_prefill_impl(
    params, cfg: LlamaConfig, tokens, pool, table, offset, valid_len
):
    b, t = tokens.shape
    q_pos = (
        jnp.asarray(offset, jnp.int32)
        + jnp.broadcast_to(jnp.arange(t), (b, t))
    )
    return _paged_forward(
        params, cfg, tokens, pool, table,
        q_pos, jnp.broadcast_to(jnp.asarray(valid_len, jnp.int32), (b,)),
    )


_paged_prefill_jit = None


def paged_prefill(
    params, cfg: LlamaConfig, tokens, pool, table, offset, valid_len
):
    """Jitted chunked prefill straight into the block pool: one
    forward over `tokens` [1, chunk] at positions [offset, offset +
    chunk) of the sequence whose block table is `table` [1, nb].
    Because the chunk shape and the table width are static while
    `offset` is traced, this compiles ONCE per (chunk, nb, model) —
    not once per prompt bucket — and a prefix-cache hit simply starts
    at a later offset with the shared blocks already in the pool.
    `pool` is donated on accelerator backends."""
    global _paged_prefill_jit
    if _paged_prefill_jit is None:
        _paged_prefill_jit = compile_watch.instrument(
            "generate.paged_prefill",
            partial(
                jax.jit,
                static_argnames=("cfg",),
                donate_argnums=accel_donate(3),
            )(_paged_prefill_impl),
        )
    return _paged_prefill_jit(
        params, cfg, tokens, pool, table, offset, valid_len
    )


def _paged_decode_step_impl(
    params,
    cfg: LlamaConfig,
    pool,
    tables,
    last_logits,
    positions,
    alive,
    key,
    temperature: float,
    top_k: int,
):
    token = _sample(last_logits, key, temperature, top_k)
    token = jnp.where(alive, token, 0)
    # Dead rows must not scatter into REAL blocks: a freed slot's
    # table is zeroed host-side, but a slot mid-admission (its table
    # already built, its prefill still running, alive not yet set)
    # would otherwise write this step's junk k/v at its STALE position
    # into the new request's — possibly shared prefix-cache — pages.
    # Masking to the null block here makes the guarantee kernel-level,
    # independent of host bookkeeping order.
    tables = jnp.where(alive[:, None], tables, 0)
    logits, pool = _paged_forward(
        params, cfg, token[:, None], pool, tables,
        positions[:, None], positions + 1,
    )
    return token, pool, logits[:, 0]


_paged_decode_jit = None


def paged_decode_step(
    params,
    cfg: LlamaConfig,
    pool,
    tables,
    last_logits,
    positions,
    alive,
    key,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
):
    """Jitted single-step decode over the FULL slot batch against the
    block pool (the paged analog of `decode_step`): sample one token
    per row from `last_logits`, scatter its k/v into each row's
    current block, and gather-attend over the row's block table.
    Compiles once per (batch, pool, table) shape. `pool` and
    `last_logits` are donated on accelerator backends — treat them as
    consumed."""
    global _paged_decode_jit
    if _paged_decode_jit is None:
        _paged_decode_jit = compile_watch.instrument(
            "generate.paged_decode_step",
            partial(
                jax.jit,
                static_argnames=("temperature", "top_k", "cfg"),
                donate_argnums=accel_donate(2, 4),
            )(_paged_decode_step_impl),
        )
    return _paged_decode_jit(
        params, cfg, pool, tables, last_logits, positions, alive, key,
        temperature=temperature, top_k=top_k,
    )


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "max_new_tokens",
        "temperature",
        "top_k",
        "eos_token",
    ),
)
def generate(
    params: Dict[str, Any],
    prompt_tokens: jax.Array,  # [b, prompt_len] padded with pad_id
    prompt_lengths: jax.Array,  # [b] true lengths
    cfg: LlamaConfig,
    *,
    max_new_tokens: int = 64,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_token: int = -1,
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (generated [b, max_new_tokens], lengths [b]).

    Static token budget; rows that emit `eos_token` stop counting (the
    returned per-row length excludes everything after EOS) but keep
    stepping — shapes stay static for XLA.
    """
    b, prompt_len = prompt_tokens.shape
    max_len = prompt_len + max_new_tokens
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    cache = init_kv_cache(cfg, b, max_len)

    # Phase 1: prefill the cache with the full (padded) prompt.
    logits, cache = _forward_with_cache(
        params,
        cfg,
        prompt_tokens,
        cache,
        jnp.int32(0),
        jnp.int32(prompt_len),
    )
    # Next-token logits come from each row's LAST VALID position.
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]

    def step(carry, key):
        cache, last_logits, position, alive = carry
        token, cache, next_logits = _decode_step(
            params, cfg, cache, last_logits, position, alive, key,
            temperature, top_k,
        )
        next_alive = alive & (token != eos_token)
        return (
            (cache, next_logits, position + 1, next_alive),
            (token, alive),
        )

    keys = jax.random.split(rng, max_new_tokens)
    # NOTE: rows shorter than prompt_len decode against a cache that
    # includes pad positions; masking uses valid_len = full prefix, so
    # equal-length prompts are exact and ragged batches approximate
    # (standard left-pad serving handles raggedness upstream).
    _, (tokens, alive_flags) = jax.lax.scan(
        step,
        (cache, last, jnp.int32(prompt_len), jnp.ones(b, bool)),
        keys,
    )
    tokens = tokens.T  # [b, max_new_tokens]
    lengths = jnp.sum(alive_flags.T.astype(jnp.int32), axis=1)
    return tokens, lengths


# Rebind through the compile watch so whole-batch generation shows up
# in `rt.diagnose()`'s verdict.compile by name instead of as
# "(unregistered)". Module-level rebinding keeps the name importable
# and picklable by reference.
generate = compile_watch.instrument("generate.generate", generate)


def generate_stream(
    params: Dict[str, Any],
    prompt_tokens: jax.Array,
    prompt_lengths: jax.Array,
    cfg: LlamaConfig,
    *,
    max_new_tokens: int = 64,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_token: int = -1,
    rng: Optional[jax.Array] = None,
    cache_len: Optional[int] = None,
):
    """Incremental analog of `generate`: yields one `[b]` int token
    array per decode step, as sampled — the producer side of token
    streaming (`num_returns="streaming"` actor methods hand each step
    to consumers while decoding continues). Trades the scan-fused
    decode loop for per-step dispatch of a single jitted step, so
    time-to-first-token is one prefill + one step instead of the whole
    budget. Stops early when every row has emitted `eos_token`.

    `cache_len` sets the KV cache to an EXACT fixed size so a serving
    caller compiles once per prompt bucket instead of once per
    (bucket, budget) pair (extra positions stay masked). It must hold
    the padded prompt AND every row's true length + budget — decode
    starts at per-row TRUE lengths, so a near-capacity request fits
    whenever true_len + max_new_tokens <= cache_len even if the
    padded bucket + budget would not."""
    import numpy as np

    b, prompt_len = prompt_tokens.shape
    if cache_len is not None:
        if cache_len != int(cache_len):
            raise ValueError(
                f"cache_len must be integral, got {cache_len!r}"
            )
        max_len = int(cache_len)
        needed = int(np.max(np.asarray(prompt_lengths)))
        if prompt_len > max_len or needed + max_new_tokens > max_len:
            raise ValueError(
                f"cache_len={max_len} cannot hold the padded prompt "
                f"({prompt_len}) and true length ({needed}) + "
                f"max_new_tokens ({max_new_tokens})"
            )
    else:
        max_len = prompt_len + max_new_tokens
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    cache = init_kv_cache(cfg, b, max_len)

    # Per-row valid lengths + decode positions: rows shorter than the
    # padded prompt start decoding at their TRUE length, so padding
    # never enters attention (each new token overwrites the pad KV at
    # its position before valid_len covers it) — unlike `generate`,
    # ragged batches are EXACT here.
    logits, cache = prefill(
        params, cfg, prompt_tokens, cache,
        jnp.int32(0), prompt_lengths.astype(jnp.int32),
    )
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]

    alive = jnp.ones(b, bool)
    position = prompt_lengths.astype(jnp.int32)
    for key in jax.random.split(rng, max_new_tokens):
        token, cache, last = decode_step(
            params, cfg, cache, last, position, alive, key,
            temperature=temperature, top_k=top_k,
        )
        alive = alive & (token != eos_token)
        yield np.asarray(token)  # rt: noqa[RT303] — the stream contract IS one host token per step; this sync is the product, not overhead
        position = position + 1
        # Post-step mask: once every row has emitted EOS there is no
        # token left to produce — stop without dispatching a dead step.
        if not np.asarray(alive).any():  # rt: noqa[RT303] — early-stop predicate must reach the host; it saves whole dead dispatches, worth one scalar sync
            return
