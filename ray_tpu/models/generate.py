"""Autoregressive decoding for the Llama family.

The serving-side counterpart of models/llama.py (the reference serves
models through vLLM-on-Ray rather than shipping its own decoder; a
TPU-native framework needs one in-tree). Decode is a two-phase jitted
program, the standard TPU inference shape:

  * prefill — one full forward over the padded prompt writes the KV
    cache (flash attention, MXU-bound);
  * decode  — `lax.scan` over steps, each a single-token forward
    against the cache (HBM-bandwidth-bound), with greedy / temperature
    / top-k sampling under a fixed token budget (static shapes; rows
    that hit EOS keep computing but emit padding — the XLA-friendly
    trade).

The KV cache layout [layers, batch, heads, max_len, head_dim] shards
over tp on heads, so tensor-parallel decode needs no cache reshuffle.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.norms import apply_rotary, rotary_embedding
from .llama import embed_tokens, model_glu, model_norm
from .llama import LlamaConfig, project_qkv


def init_kv_cache(
    cfg: LlamaConfig, batch: int, max_len: int
) -> Dict[str, jax.Array]:
    shape = (
        cfg.n_layers,
        batch,
        cfg.n_kv_heads,
        max_len,
        cfg.head_dim,
    )
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "length": jnp.zeros((batch,), jnp.int32),
    }


def _layer_with_cache(
    cfg: LlamaConfig,
    x: jax.Array,  # [b, t, dim]
    layer: Dict[str, jax.Array],
    cos,
    sin,
    k_cache,  # [b, kv_heads, max_len, hd]
    v_cache,
    cache_pos: jax.Array,  # [] start offset of x in the sequence
    valid_len: jax.Array,  # [] total valid length incl. x
):
    b, t, _ = x.shape
    hd = cfg.head_dim
    h = model_norm(cfg, x, layer["attn_norm"])
    q, k, v = project_qkv(cfg, h, layer)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k.astype(k_cache.dtype), (0, 0, cache_pos, 0)
    )
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v.astype(v_cache.dtype), (0, 0, cache_pos, 0)
    )
    max_len = k_cache.shape[2]
    groups = cfg.n_heads // cfg.n_kv_heads
    kf = jnp.repeat(k_cache, groups, axis=1)
    vf = jnp.repeat(v_cache, groups, axis=1)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = (
        jnp.einsum(
            "bhqd,bhkd->bhqk",
            q.astype(jnp.float32),
            kf.astype(jnp.float32),
        )
        * scale
    )
    # Causal + cache-validity mask over absolute positions.
    q_pos = cache_pos + jnp.arange(t)
    k_pos = jnp.arange(max_len)
    mask = (k_pos[None, :] <= q_pos[:, None]) & (
        k_pos[None, :] < valid_len
    )
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    attn = jnp.einsum("bhqk,bhkd->bhqd", probs, vf.astype(jnp.float32))
    attn = attn.astype(cfg.dtype).transpose(0, 2, 1, 3).reshape(b, t, -1)
    x = x + attn @ layer["wo"]
    h = model_norm(cfg, x, layer["mlp_norm"])
    x = x + model_glu(cfg, h @ layer["w1"], h @ layer["w3"]) @ layer["w2"]
    return x, k_cache, v_cache


def _forward_with_cache(
    params, cfg: LlamaConfig, tokens, cache, cache_pos, valid_len
):
    """tokens [b, t] -> (logits [b, t, vocab], new cache)."""
    b, t = tokens.shape
    positions = cache_pos + jnp.broadcast_to(jnp.arange(t), (b, t))
    x = embed_tokens(cfg, params, tokens)
    cos, sin = rotary_embedding(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    def body(carry, inputs):
        x = carry
        layer, k_cache, v_cache = inputs
        x, k_cache, v_cache = _layer_with_cache(
            cfg, x, layer, cos, sin, k_cache, v_cache, cache_pos,
            valid_len,
        )
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = model_norm(cfg, x, params["final_norm"])
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": cache["length"]}


def _sample(logits, key, temperature: float, top_k: int):
    """logits [b, vocab] -> token ids [b]."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        top_vals, _ = jax.lax.top_k(logits, top_k)
        cutoff = top_vals[:, -1][:, None]
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "max_new_tokens",
        "temperature",
        "top_k",
        "eos_token",
    ),
)
def generate(
    params: Dict[str, Any],
    prompt_tokens: jax.Array,  # [b, prompt_len] padded with pad_id
    prompt_lengths: jax.Array,  # [b] true lengths
    cfg: LlamaConfig,
    *,
    max_new_tokens: int = 64,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_token: int = -1,
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (generated [b, max_new_tokens], lengths [b]).

    Static token budget; rows that emit `eos_token` stop counting (the
    returned per-row length excludes everything after EOS) but keep
    stepping — shapes stay static for XLA.
    """
    b, prompt_len = prompt_tokens.shape
    max_len = prompt_len + max_new_tokens
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    cache = init_kv_cache(cfg, b, max_len)

    # Phase 1: prefill the cache with the full (padded) prompt.
    logits, cache = _forward_with_cache(
        params,
        cfg,
        prompt_tokens,
        cache,
        jnp.int32(0),
        jnp.int32(prompt_len),
    )
    # Next-token logits come from each row's LAST VALID position.
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]

    def step(carry, key):
        cache, last_logits, position, alive = carry
        token = _sample(last_logits, key, temperature, top_k)
        token = jnp.where(alive, token, 0)
        logits, cache = _forward_with_cache(
            params,
            cfg,
            token[:, None],
            cache,
            position,
            position + 1,
        )
        next_alive = alive & (token != eos_token)
        return (
            (cache, logits[:, 0], position + 1, next_alive),
            (token, alive),
        )

    keys = jax.random.split(rng, max_new_tokens)
    # NOTE: rows shorter than prompt_len decode against a cache that
    # includes pad positions; masking uses valid_len = full prefix, so
    # equal-length prompts are exact and ragged batches approximate
    # (standard left-pad serving handles raggedness upstream).
    _, (tokens, alive_flags) = jax.lax.scan(
        step,
        (cache, last, jnp.int32(prompt_len), jnp.ones(b, bool)),
        keys,
    )
    tokens = tokens.T  # [b, max_new_tokens]
    lengths = jnp.sum(alive_flags.T.astype(jnp.int32), axis=1)
    return tokens, lengths


def generate_stream(
    params: Dict[str, Any],
    prompt_tokens: jax.Array,
    prompt_lengths: jax.Array,
    cfg: LlamaConfig,
    *,
    max_new_tokens: int = 64,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_token: int = -1,
    rng: Optional[jax.Array] = None,
):
    """Incremental analog of `generate`: yields one `[b]` int token
    array per decode step, as sampled — the producer side of token
    streaming (`num_returns="streaming"` actor methods hand each step
    to consumers while decoding continues). Trades the scan-fused
    decode loop for per-step dispatch of a single jitted step, so
    time-to-first-token is one prefill + one step instead of the whole
    budget. Stops early when every row has emitted `eos_token`."""
    import numpy as np

    b, prompt_len = prompt_tokens.shape
    max_len = prompt_len + max_new_tokens
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    cache = init_kv_cache(cfg, b, max_len)

    logits, cache = _forward_with_cache(
        params, cfg, prompt_tokens, cache,
        jnp.int32(0), jnp.int32(prompt_len),
    )
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]

    @jax.jit
    def one_step(params, cache, last_logits, position, alive, key):
        token = _sample(last_logits, key, temperature, top_k)
        token = jnp.where(alive, token, 0)
        logits, cache = _forward_with_cache(
            params, cfg, token[:, None], cache, position, position + 1
        )
        return token, cache, logits[:, 0], alive & (token != eos_token)

    alive = jnp.ones(b, bool)
    position = jnp.int32(prompt_len)
    for key in jax.random.split(rng, max_new_tokens):
        token, cache, last, alive = one_step(
            params, cache, last, position, alive, key
        )
        yield np.asarray(token)  # device->host sync per step
        position = position + 1
        # Post-step mask: once every row has emitted EOS there is no
        # token left to produce — stop without dispatching a dead step.
        if not np.asarray(alive).any():
            return
