"""Model families. Flagship: Llama (BASELINE.md north star)."""

from .llama import (
    LlamaConfig,
    flops_per_token,
    forward,
    init_params,
    loss_fn,
    param_annotations,
)

__all__ = [
    "LlamaConfig",
    "forward",
    "loss_fn",
    "init_params",
    "param_annotations",
    "flops_per_token",
]
