"""Model families. Flagship: Llama (BASELINE.md north star).

Serving-side decode lives in the `generate` submodule; `prefill`/
`decode_step` are the single jitted kernels shared by
`generate.generate`, `generate_stream`, and the continuous-batching
engine (ray_tpu/llm). The `generate()` FUNCTION is deliberately not
re-exported here — it would shadow the `ray_tpu.models.generate`
submodule attribute; import it from the submodule."""

from .generate import (
    decode_step,
    generate_stream,
    init_kv_cache,
    prefill,
)
from .llama import (
    LlamaConfig,
    flops_per_token,
    forward,
    init_params,
    loss_fn,
    param_annotations,
)

__all__ = [
    "LlamaConfig",
    "forward",
    "loss_fn",
    "init_params",
    "param_annotations",
    "flops_per_token",
    "generate_stream",
    "decode_step",
    "prefill",
    "init_kv_cache",
]
